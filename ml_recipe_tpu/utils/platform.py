"""Platform-selection self-defense for entry points.

A host-side launcher (sitecustomize) may pre-import jax and pin
``jax_platforms`` at the CONFIG level before any of our code runs — an env
``JAX_PLATFORMS=cpu`` is then silently ignored (config beats env) and a CPU
debug run dials the hardware backend instead, which on a downed tunnel is
an indefinite hang, not an error. bench.py has carried this guard since
round 4; the CLI entry points route through here so a shell-level
``JAX_PLATFORMS=cpu python -m ml_recipe_tpu.cli.train ...`` behaves the
same as the documented in-process recipe.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)


def honor_env_platform() -> None:
    """Re-assert the ``JAX_PLATFORMS`` env var at the jax-config level.

    No-op when the env var is unset or a backend is already initialized
    (too late to change — jax raises, and the raise is swallowed because
    the entry point is already running on that backend by choice).
    """
    env_platforms = os.environ.get("JAX_PLATFORMS")
    if not env_platforms:
        return
    import jax

    try:
        jax.config.update("jax_platforms", env_platforms)
    except Exception as e:  # pragma: no cover - backend already initialized
        logger.debug(
            "JAX_PLATFORMS=%r not re-asserted (backend already "
            "initialized): %s", env_platforms, e,
        )
