"""Shared loader for the first-party C++ helper libraries (native/).

Single source of truth for the ``native/build/<lib>.so`` path resolution used
by both the tokenizer bindings (tokenizer/native.py) and the host-coordination
bindings (parallel/dist.py). Successful loads are cached per library name;
a missing .so is re-probed on each call so a ``make -C native`` mid-process
is picked up.
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, Optional

_BUILD_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native", "build",
)

_cache: Dict[str, ctypes.CDLL] = {}


def native_lib_path(name: str) -> str:
    return os.path.join(_BUILD_DIR, name)


def load_native_lib(name: str) -> Optional[ctypes.CDLL]:
    """CDLL for ``native/build/<name>``, or None when not built."""
    if name in _cache:
        return _cache[name]
    path = native_lib_path(name)
    if not os.path.exists(path):
        return None
    lib = ctypes.CDLL(path)
    _cache[name] = lib
    return lib
