"""Profiling hooks.

Parity target: reference ``modules/model/trainer/trainer.py:35-45``
(``time_profiler`` wall-time decorator on ``_train``/``_test``). Extended the
TPU way: a :class:`StepTimer` that accounts for XLA async dispatch (blocks on
ready before reading the clock) and an optional ``jax.profiler`` trace context
producing xplane dumps readable by TensorBoard/XProf.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Optional

# The wall-time decorator now lives on the trace plane
# (metrics/trace.py): decorated units (`_train`/`_test`) emit the same log
# line AND a `cat="profile"` span when a tracer is installed, so their
# timing rides the unified observability timeline. Public name preserved.
from ..metrics.trace import time_profiler  # noqa: F401

logger = logging.getLogger(__name__)


class StepTimer:
    """Per-step timing that is honest under XLA's async dispatch.

    Calling ``stop(result)`` blocks on ``result`` being ready before reading the
    clock, so the measured interval covers actual device execution, not just
    Python dispatch. Keeps a running mean that skips the first ``warmup`` steps
    (compilation).
    """

    _warned_no_jax = False  # once per process, not once per timer

    def __init__(self, warmup: int = 1):
        self.warmup = warmup
        self.count = 0
        self.total = 0.0
        self._t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, result=None) -> float:
        if result is not None:
            # only a missing jax is survivable (host-only environments):
            # anything else — e.g. a typo'd result tree — must surface, not
            # silently degrade every measurement to dispatch-only timing
            try:
                import jax
            except ImportError:
                if not StepTimer._warned_no_jax:
                    StepTimer._warned_no_jax = True
                    logger.warning(
                        "StepTimer: jax unavailable; timings cover Python "
                        "dispatch only, not device execution."
                    )
            else:
                jax.block_until_ready(result)
        assert self._t0 is not None, "StepTimer.stop() without start()"
        dt = time.perf_counter() - self._t0
        self._t0 = None
        self.count += 1
        if self.count > self.warmup:
            self.total += dt
        return dt

    def mean(self) -> float:
        steady = self.count - self.warmup
        return self.total / steady if steady > 0 else 0.0


@contextlib.contextmanager
def device_trace(log_dir: Optional[str]):
    """``jax.profiler`` trace context; no-op when ``log_dir`` is None."""
    if log_dir is None:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info(f"Device trace written to {log_dir}.")
