from .logging import get_logger, show_params
from .seed import set_seed, RngPool
from .profiler import time_profiler, StepTimer

__all__ = [
    "get_logger",
    "show_params",
    "set_seed",
    "RngPool",
    "time_profiler",
    "StepTimer",
]
