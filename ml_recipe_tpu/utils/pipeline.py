"""One-step-lag host/device pipelining.

The device-bound loops (train step, eval step, predictor forward) all follow
the same shape: dispatch batch N to the device, then do the host-side work
(device_get, gathers, metric/callback updates) for batch N-1 — by which time
batch N is already enqueued, so the device never idles on host work. This
helper keeps the feed/flush discipline (including the trailing flush that a
hand-rolled copy can silently forget) in one place.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Optional


class LaggedConsumer:
    """Calls ``consume(*args)`` ``depth`` feeds late; ``flush`` drains the tail.

    ``feed(*args)`` consumes the item fed ``depth`` calls ago (if any) and
    stores the new one. ``depth=1`` is the classic one-step lag; deeper lags
    keep more batches in flight — useful when each device round-trip carries
    real latency (the tunneled backend) and the consumer's fetch would
    otherwise re-serialize the pipeline. When ``total`` is given (the known
    number of feeds), the final ``feed`` drains everything immediately — so
    progress displays that close with the loop still include the last item.
    ``flush()`` consumes all stored items; call it after the loop (covers
    early exits and unknown-length streams) — it is idempotent.

    ``group > 1`` switches to GROUPED consumption: once ``depth`` items are
    in flight past a full group, the oldest ``group`` feeds are delivered in
    ONE call as ``consume([args, args, ...])`` (and ``flush`` delivers the
    tail the same way, possibly short). Use when the consumer can amortize a
    per-call cost — e.g. one device->host round trip — over the whole group.
    """

    def __init__(self, consume: Callable[..., None], total: Optional[int] = None,
                 depth: int = 1, group: int = 1):
        self._consume = consume
        self._total = total
        self._depth = max(1, depth)
        self._group = max(1, group)
        self._fed = 0
        self._pending: deque = deque()

    def _deliver_oldest(self, count: int) -> None:
        if self._group == 1:
            for _ in range(count):
                self._consume(*self._pending.popleft())
        else:
            batch = [self._pending.popleft() for _ in range(count)]
            self._consume(batch)

    def feed(self, *args) -> None:
        self._pending.append(args)
        while len(self._pending) >= self._depth + self._group:
            self._deliver_oldest(self._group)
        self._fed += 1
        if self._total is not None and self._fed >= self._total:
            self.flush()

    def flush(self) -> None:
        while self._pending:
            self._deliver_oldest(
                min(self._group, len(self._pending))
            )
