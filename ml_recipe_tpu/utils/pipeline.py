"""One-step-lag host/device pipelining.

The device-bound loops (train step, eval step, predictor forward) all follow
the same shape: dispatch batch N to the device, then do the host-side work
(device_get, gathers, metric/callback updates) for batch N-1 — by which time
batch N is already enqueued, so the device never idles on host work. This
helper keeps the feed/flush discipline (including the trailing flush that a
hand-rolled copy can silently forget) in one place.
"""

from __future__ import annotations

from typing import Callable, Optional


class LaggedConsumer:
    """Calls ``consume(*args)`` one ``feed`` late; ``flush`` drains the tail.

    ``feed(*args)`` consumes the PREVIOUSLY fed item (if any) and stores the
    new one. When ``total`` is given (the known number of feeds), the final
    ``feed`` consumes its own item immediately — so progress displays that
    close with the loop still include the last item. ``flush()`` consumes
    any stored item; call it after the loop (covers early exits and
    unknown-length streams) — it is idempotent.
    """

    def __init__(self, consume: Callable[..., None], total: Optional[int] = None):
        self._consume = consume
        self._total = total
        self._fed = 0
        self._pending = None

    def feed(self, *args) -> None:
        if self._pending is not None:
            self._consume(*self._pending)
        self._pending = args
        self._fed += 1
        if self._total is not None and self._fed >= self._total:
            self.flush()

    def flush(self) -> None:
        if self._pending is not None:
            pending, self._pending = self._pending, None
            self._consume(*pending)
