"""Device-memory byte arithmetic shared by the HBM pre-flight planners.

One definition of "projected per-device bytes" for both planners:
``Trainer.preflight_train_step`` (raises ``batch_split`` instead of an XLA
train-step OOM) and ``QAEngine.preflight_predict_step`` (shrinks the
serving bucket grid instead of OOMing mid-traffic). Lives in utils so the
serving request path does not import the training stack.
"""

from __future__ import annotations

from typing import Optional

import jax


def device_hbm_bytes() -> Optional[int]:
    """Per-device HBM capacity in bytes, or ``None`` when the backend does
    not report one (CPU; some simulators) — the pre-flight planner then
    stands down rather than guessing."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 - absent API = no limit knowledge
        return None
    if not stats:
        return None
    limit = stats.get("bytes_limit")
    return int(limit) if limit else None


def preflight_bytes(memory_analysis) -> Optional[int]:
    """Projected per-device HBM requirement of a compiled step: arguments +
    outputs + temporaries, minus the donated-buffer aliasing (donated
    inputs' output copies reuse the argument buffers). ``None`` when the
    analysis is unavailable or malformed — the planner then stands down
    instead of acting on garbage."""
    if memory_analysis is None:
        return None
    try:
        need = (
            int(memory_analysis.argument_size_in_bytes)
            + int(memory_analysis.output_size_in_bytes)
            + int(memory_analysis.temp_size_in_bytes)
            - int(getattr(memory_analysis, "alias_size_in_bytes", 0))
        )
    except (AttributeError, TypeError, ValueError):
        return None
    return need if need > 0 else None
