"""Logging utilities.

Parity target: reference ``modules/utils.py:10-51`` (``get_logger`` resets root
handlers, installs console + optional file handler, debug pathname format;
``show_params`` dumps the effective config). Re-designed for one-process-per-host
SPMD: non-zero processes log at WARN by default so multi-host output stays
readable (the reference gated this per-rank in ``modules/train.py:37-39``).
"""

from __future__ import annotations

import logging
import sys
from typing import Optional


def get_logger(
    *,
    level: int = logging.INFO,
    filename: Optional[str] = None,
    filemode: str = "w",
    logger_name: Optional[str] = None,
    debug: bool = False,
) -> logging.Logger:
    """Reset root logging config and return a named logger.

    Mirrors the reference's handler-resetting behaviour so repeated calls
    (e.g. notebook re-runs) do not duplicate handlers.
    """
    for handler in logging.root.handlers[:]:
        logging.root.removeHandler(handler)

    handlers: list[logging.Handler] = [logging.StreamHandler(sys.stderr)]
    if filename is not None:
        handlers.append(logging.FileHandler(filename, filemode))

    path_format = "%(pathname)s:%(funcName)s:%(lineno)d" if debug else "%(name)s"

    logging.basicConfig(
        format=f"%(asctime)s - %(levelname)s - {path_format} -   %(message)s",
        datefmt="%m/%d/%Y %H:%M:%S",
        level=level,
        handlers=handlers,
    )

    # Third-party chatter we never want at INFO.
    for noisy in ("jax._src", "absl", "orbax"):
        logging.getLogger(noisy).setLevel(logging.WARNING)

    logger = logging.getLogger(logger_name if logger_name is not None else __name__)
    if filename is not None and filemode == "w":
        logger.info(f"All logs will be dumped to {filename}.")

    return logger


def show_params(params, name: str, logger: Optional[logging.Logger] = None) -> None:
    """Log every field of a config namespace/dataclass, sorted by name."""
    log = logger or logging.getLogger(__name__)
    log.info(f"Input {name} parameters:")
    fields = params.__dict__ if hasattr(params, "__dict__") else dict(params)
    for k in sorted(fields.keys()):
        log.info(f"\t\t{k}: {fields[k]}")
