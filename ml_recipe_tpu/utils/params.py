"""Param-tree validation shared by checkpoint restore and HF warm-start.

``flax.serialization.from_state_dict`` is structural, not shape-checked,
and flax ``apply`` never re-validates param shapes — XLA's clamp-mode
gathers can then make wrong-shaped tables invisible until quality numbers
come in (review r5). Every path that swaps arrays into a live param tree
routes through this one check so the error message and the rule cannot
drift between callers."""

from __future__ import annotations

import numpy as np


def check_param_shapes(target, restored, context: str) -> None:
    """Raise ``ValueError`` when any restored leaf's shape differs from the
    model's. ``context`` names the source (checkpoint path, HF name) for
    the error message. Callers guarantee matching tree structure
    (``from_state_dict`` enforces it; the HF converter builds the same
    schema)."""
    import jax

    mismatched = [
        f"{jax.tree_util.keystr(kp)}: source {np.shape(b)} vs model "
        f"{np.shape(a)}"
        for (kp, a), b in zip(
            jax.tree_util.tree_flatten_with_path(target)[0],
            jax.tree_util.tree_leaves(restored),
        )
        if np.shape(a) != np.shape(b)
    ]
    if mismatched:
        raise ValueError(
            f"{context} does not fit the model config; mismatched param "
            f"shapes at: {mismatched[:5]}"
        )
