"""Determinism utilities.

Parity target: reference ``modules/utils.py:34-45`` (``set_seed`` seeds python /
numpy / torch and flips cuDNN determinism knobs). On TPU the device-side story
is simpler: JAX PRNG is deterministic by construction, so only the *host-side*
RNGs (python ``random``, numpy — used for weighted chunk sampling and shuffles)
need seeding, plus a root ``jax.random`` key for device-side randomness
(dropout), which we thread explicitly through the train step.
"""

from __future__ import annotations

import logging
import random
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)


def set_seed(seed: Optional[int] = None) -> Optional["RngPool"]:
    """Seed host RNGs and build the root device-key pool.

    Returns an :class:`RngPool` (or ``None`` when ``seed`` is ``None``, matching
    the reference's behaviour of leaving RNGs unseeded unless asked).
    """
    if seed is None:
        return None

    random.seed(seed)
    np.random.seed(seed)

    logger.info(
        f"Random seed was set to {seed}. Host numpy/python RNGs seeded; "
        f"device randomness is keyed from the same seed."
    )
    return RngPool(seed)


@dataclass
class RngPool:
    """Deterministic source of ``jax.random`` keys.

    The reference relied on global torch/cuDNN seeding; JAX requires explicit
    key threading. The pool hands out a fresh fold of the root key per
    (purpose, step) pair so dropout/BPE-dropout streams never collide and
    resuming at step N reproduces the exact key sequence.
    """

    seed: int
    _purposes: dict = field(default_factory=dict)

    def key(self, purpose: str, step: int = 0):
        import jax

        if purpose not in self._purposes:
            self._purposes[purpose] = len(self._purposes) + 1
        root = jax.random.key(self.seed)
        return jax.random.fold_in(jax.random.fold_in(root, self._purposes[purpose]), step)

    def host_rng(self, purpose: str, step: int = 0) -> np.random.Generator:
        """Numpy generator for host-side sampling (weighted chunk choice)."""
        if purpose not in self._purposes:
            self._purposes[purpose] = len(self._purposes) + 1
        return np.random.default_rng(
            np.random.SeedSequence([self.seed, self._purposes[purpose], step])
        )
