"""Loss zoo — pure-JAX, jit-safe.

Parity targets (reference ``modules/model/model/loss.py`` semantics, checked
numerically against torch in tests):

- ``cross_entropy_with_ignore``: ``nn.CrossEntropyLoss(ignore_index=-1)`` as
  used for span start/end heads (init.py:34-35) — mean over non-ignored rows;
  optional per-class weights reproduce ``CrossEntropyLoss(weight=...)``
  (init.py:23) including its weighted-mean denominator.
- ``label_smoothing_loss``: ``LabelSmoothingLossWithLogits`` (loss.py:5-38) —
  KLDiv-batchmean against the smoothed target distribution when smoothing>0
  (smoothing mass split over ``n_classes - num_ignore``), NLL otherwise.
- ``binary_focal_loss``: ``BinaryFocalLossWithLogits`` (loss.py:41-54).
- ``focal_loss``: ``FocalLossWithLogits`` (loss.py:57-71) — focal reweighting
  applied *inside* the NLL pick, with ignore-index masking.
- ``mse_loss``: ``nn.MSELoss`` for the position regressors (init.py:36-37).
- ``WeightedLoss``: the per-head aggregator (loss.py:74-106). Functional
  twist: instead of mutating AverageMeters inside the loss (impossible under
  jit), ``__call__`` returns ``(total, per_head_values)`` and the trainer
  feeds meters host-side.

All losses take f32 logits (the model promotes) and integer/float targets.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def _log_softmax(logits):
    return jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)


def cross_entropy_with_ignore(
    logits: jnp.ndarray,
    targets: jnp.ndarray,
    *,
    ignore_index: int = -1,
    class_weights: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Mean NLL over rows whose target != ignore_index.

    With ``class_weights`` the mean is weighted by the target's class weight
    (torch ``CrossEntropyLoss(weight=...)`` denominator semantics).
    """
    log_probs = _log_softmax(logits)
    valid = targets != ignore_index
    safe_targets = jnp.where(valid, targets, 0)

    nll = -jnp.take_along_axis(log_probs, safe_targets[..., None], axis=-1)[..., 0]

    if class_weights is not None:
        w = class_weights[safe_targets] * valid
        return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1e-12)

    valid_f = valid.astype(jnp.float32)
    return jnp.sum(nll * valid_f) / jnp.maximum(jnp.sum(valid_f), 1.0)


def label_smoothing_loss(
    logits: jnp.ndarray,
    targets: jnp.ndarray,
    *,
    n_classes: int,
    smoothing: float = 0.0,
    ignore_index: int = -100,
    valid: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """``valid`` (optional bool [N]) restricts the mean to those rows — the
    packed-segment path (KLDiv batchmean has no ignore_index of its own, so
    absent segments must be masked out of the mean explicitly). ``None``
    keeps the historical whole-batch arithmetic bit-exactly."""
    assert 0 <= smoothing <= 1
    log_probs = _log_softmax(logits)

    if smoothing <= 0:
        if valid is not None:
            targets = jnp.where(valid, targets, ignore_index)
        return cross_entropy_with_ignore(logits, targets, ignore_index=ignore_index)

    num_ignore = 1 + (0 <= ignore_index < n_classes)
    fill_value = smoothing / (n_classes - num_ignore)
    confidence = 1.0 - smoothing

    safe_targets = targets if valid is None else jnp.where(valid, targets, 0)
    target_dist = jnp.full((targets.shape[0], n_classes), fill_value, dtype=jnp.float32)
    target_dist = jnp.asarray(target_dist).at[
        jnp.arange(targets.shape[0]), safe_targets
    ].set(confidence)
    if 0 <= ignore_index < n_classes:
        target_dist = target_dist.at[:, ignore_index].set(0.0)

    # KLDivLoss(reduction='batchmean'): sum over classes of t*(log t - log p),
    # averaged over the batch; 0*log(0) := 0.
    t_log_t = jnp.where(target_dist > 0, target_dist * jnp.log(target_dist), 0.0)
    kl = jnp.sum(t_log_t - target_dist * log_probs, axis=-1)
    if valid is None:
        return jnp.mean(kl)
    v = valid.astype(jnp.float32)
    return jnp.sum(kl * v) / jnp.maximum(jnp.sum(v), 1.0)


def binary_focal_loss(
    logits: jnp.ndarray, targets: jnp.ndarray, *, alpha: float = 1.0, gamma: float = 2.0
) -> jnp.ndarray:
    logits = logits.astype(jnp.float32)
    targets = targets.astype(jnp.float32)
    # stable BCE-with-logits
    bce = jnp.maximum(logits, 0) - logits * targets + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    probs = jnp.exp(-bce)
    return jnp.mean(alpha * (1 - probs) ** gamma * bce)


def focal_loss(
    logits: jnp.ndarray,
    targets: jnp.ndarray,
    *,
    alpha: float = 1.0,
    gamma: float = 2.0,
    ignore_index: int = -1,
) -> jnp.ndarray:
    log_probs = _log_softmax(logits)
    probs = jnp.exp(log_probs)
    weighted = alpha * (1 - probs) ** gamma * log_probs

    valid = targets != ignore_index
    safe_targets = jnp.where(valid, targets, 0)
    picked = -jnp.take_along_axis(weighted, safe_targets[..., None], axis=-1)[..., 0]

    valid_f = valid.astype(jnp.float32)
    return jnp.sum(picked * valid_f) / jnp.maximum(jnp.sum(valid_f), 1.0)


def mse_loss(preds: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean((preds.astype(jnp.float32) - targets.astype(jnp.float32)) ** 2)


class WeightedLoss:
    """Weighted sum of per-head losses (reference loss.py:74-106).

    ``losses`` maps head name -> (loss_fn, weight). ``__call__`` returns
    ``(total_loss, {head: value})``; per-head values are the *unweighted*
    losses, matching what the reference logged into its meters.
    """

    def __init__(self, losses: Dict[str, Tuple[Callable, float]]):
        self._losses = losses

    @property
    def keys(self):
        return self._losses.keys()

    def value_structure(self) -> dict:
        """Zero-valued dict with the shape of ``__call__``'s values output —
        used as the scan carry init for in-step gradient accumulation."""
        out = {key: 0.0 for key in self._losses}
        out["loss"] = 0.0
        return out

    def __call__(self, preds: dict, targets: dict) -> Tuple[jnp.ndarray, dict]:
        assert set(preds.keys()) >= set(self._losses.keys())
        assert set(targets.keys()) >= set(self._losses.keys())

        values = {}
        full_loss = 0.0
        for key, (loss_f, weight) in self._losses.items():
            loss = loss_f(preds[key], targets[key])
            values[key] = loss
            full_loss = full_loss + weight * loss

        values["loss"] = full_loss
        return full_loss, values


def masked_mse_loss(preds: jnp.ndarray, targets: jnp.ndarray,
                    valid: jnp.ndarray) -> jnp.ndarray:
    """``mse_loss`` over rows where ``valid`` only (packed-segment variant:
    absent segments carry zero predictions/targets that must not dilute the
    mean)."""
    v = valid.astype(jnp.float32)
    sq = (preds.astype(jnp.float32) - targets.astype(jnp.float32)) ** 2
    return jnp.sum(sq * v) / jnp.maximum(jnp.sum(v), 1.0)


class PackedWeightedLoss:
    """``WeightedLoss`` adapter for sequence-packed batches.

    Predictions arrive per SEGMENT (``[R, S, ...]`` — the packed QAModel's
    head outputs) and targets carry a ``segment_mask`` validity plane
    (data/packing.collate_packed). Every head is computed over the
    flattened ``R*S`` segment axis with absent segments excluded: the span
    and class heads reuse the base loss functions verbatim by rewriting
    absent segments' targets to the head's ignore_index (span CE already
    ignores -1, class CE -100, focal -1); mse and smoothing>0 — which have
    no ignore semantics — go through the masked variants above. Returned
    values are means over REAL segments (= original examples), so the
    trainer's row-weighted epoch meters stay per-example-correct when
    weighted by the batch's real segment count.
    """

    def __init__(self, base: WeightedLoss):
        import functools as _ft

        self.base = base
        self._losses = base._losses
        self._cls_fns = {}
        for key, (fn, _weight) in base._losses.items():
            if key in ("start_class", "end_class", "start_reg", "end_reg"):
                continue
            base_fn = fn.func if isinstance(fn, _ft.partial) else fn
            kw = dict(fn.keywords) if isinstance(fn, _ft.partial) else {}
            if base_fn is label_smoothing_loss:
                self._cls_fns[key] = ("smooth", kw)
            elif base_fn is cross_entropy_with_ignore:
                self._cls_fns[key] = ("ignore", kw.get("ignore_index", -1))
            elif base_fn is focal_loss:
                self._cls_fns[key] = ("ignore", kw.get("ignore_index", -1))
            else:
                raise NotImplementedError(
                    f"PackedWeightedLoss cannot adapt head {key!r} "
                    f"({base_fn}): no ignore/mask semantics known"
                )

    @property
    def keys(self):
        return self.base.keys

    def value_structure(self) -> dict:
        return self.base.value_structure()

    def __call__(self, preds: dict, targets: dict) -> Tuple[jnp.ndarray, dict]:
        valid = targets["segment_mask"].reshape(-1) > 0

        def flat(x):
            x = jnp.asarray(x)
            return x.reshape((-1,) + x.shape[2:])

        values = {}
        full_loss = 0.0
        for key, (loss_f, weight) in self._losses.items():
            p, t = flat(preds[key]), flat(targets[key])
            if key in ("start_class", "end_class"):
                # span CE ignores -1 — absent segments carry -1 already
                # (collate) but pad ROWS repeat real labels, so re-mask
                loss = loss_f(p, jnp.where(valid, t, -1))
            elif key in ("start_reg", "end_reg"):
                loss = masked_mse_loss(p, t, valid)
            else:
                kind, arg = self._cls_fns[key]
                if kind == "smooth":
                    loss = label_smoothing_loss(p, t, valid=valid, **arg)
                else:
                    loss = loss_f(p, jnp.where(valid, t, arg))
            values[key] = loss
            full_loss = full_loss + weight * loss

        values["loss"] = full_loss
        return full_loss, values


def build_loss(params, train_weights: Optional[dict] = None) -> WeightedLoss:
    """Select the classification loss + per-head weights (init.py:18-40)."""
    import functools

    label_weights = None
    if train_weights is not None and train_weights.get("label_weights") is not None:
        label_weights = jnp.asarray(train_weights["label_weights"], dtype=jnp.float32)

    n_classes = 5
    if params.loss == "ce":
        class_loss = functools.partial(
            cross_entropy_with_ignore, ignore_index=-100, class_weights=label_weights
        )
    elif params.loss == "focal":
        # reference FocalLossWithLogits defaults to ignore_index=-1 (loss.py:59)
        class_loss = functools.partial(
            focal_loss, alpha=params.focal_alpha, gamma=params.focal_gamma,
            ignore_index=-1,
        )
    elif params.loss == "smooth":
        class_loss = functools.partial(
            label_smoothing_loss, n_classes=n_classes, smoothing=params.smooth_alpha
        )
    else:
        raise NotImplementedError(f"Unknown loss {params.loss}")

    def _wght(name):
        return getattr(params, name, 1)

    span_ce = functools.partial(cross_entropy_with_ignore, ignore_index=-1)

    return WeightedLoss(
        {
            "start_class": (span_ce, _wght("w_start")),
            "end_class": (span_ce, _wght("w_end")),
            "start_reg": (mse_loss, _wght("w_start_reg")),
            "end_reg": (mse_loss, _wght("w_end_reg")),
            "cls": (class_loss, _wght("w_cls")),
        }
    )
