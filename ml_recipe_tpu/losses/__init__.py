from .losses import (
    cross_entropy_with_ignore,
    label_smoothing_loss,
    binary_focal_loss,
    focal_loss,
    mse_loss,
    masked_mse_loss,
    WeightedLoss,
    PackedWeightedLoss,
    build_loss,
)

__all__ = [
    "cross_entropy_with_ignore",
    "label_smoothing_loss",
    "binary_focal_loss",
    "focal_loss",
    "mse_loss",
    "masked_mse_loss",
    "WeightedLoss",
    "PackedWeightedLoss",
    "build_loss",
]
