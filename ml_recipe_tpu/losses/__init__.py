from .losses import (
    cross_entropy_with_ignore,
    label_smoothing_loss,
    binary_focal_loss,
    focal_loss,
    mse_loss,
    WeightedLoss,
    build_loss,
)

__all__ = [
    "cross_entropy_with_ignore",
    "label_smoothing_loss",
    "binary_focal_loss",
    "focal_loss",
    "mse_loss",
    "WeightedLoss",
    "build_loss",
]
