"""Re-export shim: the metric primitives live in ``ml_recipe_tpu.metrics.registry``.

They started here as the serving plane's first-party Prometheus-text
metrics; the unified observability plane lifted them into the shared
``metrics`` package so the training exporter uses the same primitives.
Existing ``serve.metrics`` importers keep working through this shim.
"""

from __future__ import annotations

from ..metrics.registry import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Info,
    Registry,
    _fmt,
)

__all__ = [
    "DEFAULT_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "Info",
    "Registry",
]
