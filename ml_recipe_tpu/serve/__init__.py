"""Online serving subsystem: continuous-batching QA inference over a fixed
grid of pre-compiled ``(batch, seq)`` bucket programs.

Layers (each importable on its own):

- :mod:`.bucketing` — the bucket grid + pad-to-bucket admission (also home
  to ``pad_trailing_batch``, shared with ``infer/predictor.py``);
- :mod:`.batcher` — deadline-coalescing micro-batch queue with bounded-queue
  backpressure;
- :mod:`.engine` — request chunking, scatter into shared batches, and
  per-request span reduction through the same jitted score function as the
  batch predictor (``infer/score.py``);
- :mod:`.metrics` — first-party Prometheus-text Counter/Gauge/Histogram;
- :mod:`.server` — stdlib HTTP front end (``POST /v1/qa``, ``/healthz``,
  ``/metrics``) with SIGTERM drain.

``engine``/``server`` are imported lazily: ``infer/predictor.py`` imports
``serve.bucketing`` for the shared pad helper, and an eager engine import
here would create an import cycle back through ``infer``.
"""

from __future__ import annotations

from .batcher import ChunkWork, DrainingError, MicroBatcher, QueueFullError
from .bucketing import Bucket, BucketGrid, parse_bucket_spec, pad_trailing_batch
from .metrics import Counter, Gauge, Histogram, Registry

__all__ = [
    "Bucket", "BucketGrid", "parse_bucket_spec", "pad_trailing_batch",
    "ChunkWork", "DrainingError", "MicroBatcher", "QueueFullError",
    "Counter", "Gauge", "Histogram", "Registry",
    "QAEngine", "QAResult", "RequestTicket", "RequestRejected",
    "QAServer",
]

_LAZY = {
    "QAEngine": "engine", "QAResult": "engine", "RequestTicket": "engine",
    "RequestRejected": "engine", "QAServer": "server",
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        module = importlib.import_module(f".{_LAZY[name]}", __name__)
        return getattr(module, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
