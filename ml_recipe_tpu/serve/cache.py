"""Serving hot-path caches: byte-budgeted LRU tiers for the QA engine.

Two independent tiers, both off by default and both strictly
transparency-preserving (a hit returns the exact object a miss would have
computed, so cached and uncached responses are bit-identical by
construction — pinned in tests/test_serve_cache.py):

- **Tier 1 — document preprocessing cache** (``--doc_cache_bytes``): the
  ``encode_document`` token stream (the offset maps are train/eval-only —
  serving discards them) and the ``window_chunks`` layout, keyed by a
  content hash of the raw document text. Tokenization is question-
  independent by construction; the window layout depends on the question
  only through its token LENGTH (``document_len = max_seq - q_len - 3``),
  so its key carries ``(doc_hash, question_len, max_seq, doc_stride)`` —
  the same document asked a hundred different questions of tokenizes
  once. Hot documents skip host tokenization entirely.

- **Tier 2 — chunk-result cache** (``--serve_cache_bytes``): the packed
  span-logit output row of one device input row, keyed by a hash of the
  EXACT ``assemble_input_ids`` output plus a checkpoint fingerprint and
  the active precision (``bf16``/``int8`` are distinct keys, mirroring
  the autotuner's ``q8`` suffix discipline — same bytes through a
  different arithmetic are a different result). A hit bypasses the
  micro-batcher and offers its row to the ticket directly: a fully-hot
  request never touches the TPU, and a partially-hot request (the same
  question over an edited/grown document) only computes the changed
  windows. The tier additionally runs SINGLE-FLIGHT dedup: identical
  chunks already in flight are joined as waiters instead of re-enqueued,
  so a burst of the same question/document pair costs one device row.

Both tiers are byte-budgeted LRUs with exact accounting: an insert that
would exceed the budget evicts least-recently-used entries first, and an
entry whose own cost exceeds the whole budget is refused outright (storing
it would evict everything and still not fit). Budget 0 disables a tier
completely — the engine then never computes keys, registers flights, or
touches this module's locks on the request path.

The per-document affinity this cache rewards is exactly what the ROADMAP's
fleet front (c) consistent-hash router is designed to feed.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "ByteBudgetLRU", "ChunkResultCache", "content_key", "row_key",
    "params_fingerprint",
]

# documented cost model for the byte budget: python object overhead per
# cached entry (key string + OrderedDict node + value holder), plus a
# per-token charge for the payloads. Token streams and window records are
# stored as the Python int lists the hot path consumes directly — a
# small-int list slot really costs ~36 B (28 B int object + 8 B pointer),
# and charging the int32 wire size instead would let resident memory
# overshoot the configured budget ~9x
ENTRY_OVERHEAD = 96
TOKEN_BYTES = 36


def content_key(text: str) -> str:
    """Stable content hash of one raw document text (tier-1 key root)."""
    return hashlib.sha256(text.encode("utf-8", "surrogatepass")).hexdigest()[:32]


def row_key(fingerprint: str, precision: str, input_ids) -> str:
    """Tier-2 key of one exact device input row.

    ``input_ids`` is the ``assemble_input_ids`` output (``[CLS] question
    [SEP] chunk [SEP]``) — hashing the final row means ANY difference that
    could change the model output (question text, chunk bytes, truncation)
    changes the key, while padding (applied later, to the bucket shape)
    cannot: the score function masks pad rows identically regardless of
    bucket, so one row has one result.
    """
    import numpy as np

    digest = hashlib.sha256(
        np.asarray(input_ids, np.int32).tobytes()
    ).hexdigest()[:32]
    return f"{fingerprint}|{precision or 'off'}|{digest}"


# leaves larger than this are fingerprinted by head + tail + byte count
# instead of a full hash: checkpoints that differ at all differ pervasively
# (every step updates every moment/weight), so sampling is collision-safe in
# practice while keeping the startup device->host copy bounded
_FP_SAMPLE_BYTES = 1 << 20


def params_fingerprint(params) -> str:
    """Checkpoint fingerprint: a content hash over the parameter pytree
    (leaf paths, dtypes, shapes, and leaf bytes — large leaves sampled
    head/tail). Computed once at engine startup, only when the tier-2
    cache is enabled; two engines serving different checkpoints can then
    never alias each other's cached rows even if they share a cache
    object."""
    import jax
    import numpy as np

    h = hashlib.sha256()
    for path, leaf in sorted(
        jax.tree_util.tree_flatten_with_path(params)[0],
        key=lambda kv: jax.tree_util.keystr(kv[0]),
    ):
        dtype = np.dtype(leaf.dtype) if hasattr(leaf, "dtype") else \
            np.asarray(leaf).dtype
        size = int(np.prod(leaf.shape)) if hasattr(leaf, "shape") else \
            np.asarray(leaf).size
        nbytes = size * dtype.itemsize
        h.update(jax.tree_util.keystr(path).encode())
        h.update(str(dtype).encode())
        h.update(str(getattr(leaf, "shape", ())).encode())
        if nbytes > 2 * _FP_SAMPLE_BYTES:
            # slice DEVICE-SIDE before materializing on host — the bound
            # must hold for the transfer, not just the hashing
            n = max(1, _FP_SAMPLE_BYTES // dtype.itemsize)
            flat = leaf.reshape(-1)
            h.update(np.asarray(flat[:n]).tobytes())
            h.update(np.asarray(flat[-n:]).tobytes())
            h.update(str(nbytes).encode())
        else:
            h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()[:24]


class ByteBudgetLRU:
    """Thread-safe LRU over string keys with exact byte accounting.

    Every entry carries the caller-declared ``cost`` in bytes; inserts past
    ``budget_bytes`` evict least-recently-used entries until the new entry
    fits. ``get`` refreshes recency. Stats (``hits``/``misses``/
    ``evictions``/``bytes``) are plain monotonic counters the engine
    mirrors into its Prometheus registry.
    """

    def __init__(self, budget_bytes: int):
        self.budget_bytes = int(budget_bytes)
        self._entries: "OrderedDict[str, Tuple[Any, int]]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._lock = threading.RLock()

    @property
    def lock(self) -> threading.RLock:
        return self._lock

    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get(self, key: str):
        """Cached value (refreshing recency) or None."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry[0]

    def put(self, key: str, value, cost: int) -> int:
        """Insert (or refresh) ``key``; returns how many entries were
        evicted to make room. An entry whose own cost exceeds the whole
        budget is refused (it would evict everything and still not fit);
        a refreshed key's old cost is released first."""
        cost = int(cost)
        evicted = 0
        with self._lock:
            if cost > self.budget_bytes:
                old = self._entries.pop(key, None)
                if old is not None:
                    self._bytes -= old[1]
                return evicted
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            while self._entries and self._bytes + cost > self.budget_bytes:
                _, (_, old_cost) = self._entries.popitem(last=False)
                self._bytes -= old_cost
                self.evictions += 1
                evicted += 1
            self._entries[key] = (value, cost)
            self._bytes += cost
        return evicted

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions, "bytes": self._bytes,
                "entries": len(self._entries),
            }


class ChunkResultCache(ByteBudgetLRU):
    """Tier 2: chunk-result LRU + single-flight dedup of in-flight rows.

    The flight table maps a row key to the list of WAITERS piggybacking on
    the one enqueued computation (the leader's ``ChunkWork``). The engine
    holds :attr:`lock` across classify-and-admit in ``submit`` so the
    flight table and the batcher admission stay atomic: a flight the
    engine leases is guaranteed to reach the queue (or be aborted under
    the same lock hold) before any other thread can observe it.
    """

    def __init__(self, budget_bytes: int):
        super().__init__(budget_bytes)
        self._flight: Dict[str, List[Tuple[Any, int]]] = {}
        # both MONOTONIC (the engine mirrors them into Prometheus
        # counters): joins count every piggyback as it happens, rollbacks
        # count joins later undone by admission failure — net dedup wins
        # are joins - rollbacks
        self.flight_joins = 0
        self.flight_join_rollbacks = 0

    def join_flight(self, key: str, waiter: Tuple[Any, int]) -> bool:
        """True = an identical row is already in flight and ``waiter`` was
        appended to it; False = no flight existed and one was LEASED (the
        caller must enqueue the row, then ``complete``/``abort`` it)."""
        with self._lock:
            waiters = self._flight.get(key)
            if waiters is not None:
                waiters.append(waiter)
                self.flight_joins += 1
                return True
            self._flight[key] = []
            return False

    def complete(self, key: str, row, cost: int) -> Tuple[List[Tuple[Any, int]], int]:
        """The leader's row arrived: cache it (LRU rules) and return
        ``(waiters, evicted)`` — every waiter gets the SAME row object the
        leader does."""
        with self._lock:
            waiters = self._flight.pop(key, [])
            evicted = self.put(key, row, cost)
        return waiters, evicted

    def fail_flight(self, key: str) -> List[Tuple[Any, int]]:
        """The leader's batch failed: nothing is cached; the waiters are
        returned so the engine can fail their tickets too."""
        with self._lock:
            return self._flight.pop(key, [])

    def abort_flight(self, key: str) -> None:
        """Admission of the leased leader failed (queue full / draining):
        forget the flight. Only callable under the same :attr:`lock` hold
        that leased it — no waiter can have joined in between."""
        with self._lock:
            self._flight.pop(key, None)

    def remove_waiters(self, owner) -> int:
        """Drop every waiter whose ticket IS ``owner`` (admission rollback
        of a request that joined flights — other requests' or its own
        just-leased ones). ``flight_joins`` stays monotonic; the undo is
        recorded in ``flight_join_rollbacks``."""
        removed = 0
        with self._lock:
            for waiters in self._flight.values():
                kept = [w for w in waiters if w[0] is not owner]
                removed += len(waiters) - len(kept)
                waiters[:] = kept
            self.flight_join_rollbacks += removed
        return removed

    def inflight(self) -> int:
        with self._lock:
            return len(self._flight)
