"""Online QA inference engine: request -> chunks -> shared batches -> span.

Turns the offline packed-forward predictor into a long-running
request/response engine:

- each request's document is sliding-window chunked host-side (the same
  ``data/chunking.py`` machinery the datasets use — chunk geometry is
  data-dependent and stays outside jit);
- chunks are scattered into the continuous micro-batcher
  (``batcher.MicroBatcher``), which coalesces concurrent requests into
  ``(batch, seq)`` buckets from the fixed grid (``bucketing.BucketGrid``) —
  the whole traffic distribution is served by N long-lived compiled
  programs, warmed at startup;
- every batch runs the SAME jitted scoring forward as the batch predictor
  (``infer/score.py``: model forward + arXiv 1901.08634 answerability score,
  ONE packed [6, B] f32 fetch per batch), so serving spans match
  ``infer/predictor.py`` for the same inputs by construction;
- when a request's last chunk lands, chunks are reduced IN CHUNK ORDER with
  the predictor's exact validity rules (span order, answer not inside the
  question, best-score-wins with predictor tie semantics), and the winning
  span is decoded back to text;
- two optional byte-budgeted caches short-circuit the hot path
  (``serve/cache.py``, off by default): document preprocessing by content
  hash, and per-chunk result rows by exact-device-row hash + checkpoint
  fingerprint + precision with single-flight dedup — cache-hit chunks
  bypass the micro-batcher entirely, and responses are bit-identical
  cached or not.

HBM pre-flight (``preflight_predict_step``): at warmup each bucket's program
is lowered + compiled once and XLA's ``memory_analysis()`` is read; a bucket
whose projected requirement exceeds device HBM is DROPPED FROM THE GRID
(logged) instead of OOMing mid-traffic — the ROADMAP's "extend the
pre-flight to eval/predict steps" item, sharing the byte arithmetic with
``Trainer.preflight_train_step``.

Everything here runs under ``JAX_PLATFORMS=cpu`` for tier-1: buckets compile
on CPU and the request path has no TPU-only branches.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..data import RawPreprocessor
from ..data.chunking import (
    assemble_input_ids,
    encode_document,
    window_chunks,
)
from ..infer.score import OUT_KEYS, build_score_fn
from ..ops import aot, autotune
from ..parallel import ParallelPlan, build_mesh, make_global_array
# the HBM byte arithmetic is shared with Trainer.preflight_train_step — one
# definition of "projected per-device bytes" for train and predict steps
# (utils/hbm.py: the serving path must not import the training stack)
from ..metrics import trace as trace_mod
from ..utils.hbm import device_hbm_bytes, preflight_bytes
from .batcher import ChunkWork, DrainingError, MicroBatcher, QueueFullError
from .bucketing import Bucket, BucketGrid, pad_trailing_batch
from .cache import (
    ENTRY_OVERHEAD,
    TOKEN_BYTES,
    ByteBudgetLRU,
    ChunkResultCache,
    content_key,
    params_fingerprint,
    row_key,
)
from .metrics import Registry

logger = logging.getLogger(__name__)

__all__ = [
    "QAEngine", "QAResult", "RequestTicket", "RequestRejected",
    "QueueFullError", "DrainingError",
]


class RequestRejected(ValueError):
    """The request cannot be admitted at all (over-long question, empty
    document) — a client error, not backpressure."""


@dataclass
class QAResult:
    """Final per-request answer."""

    answer: str
    label: str           # 'yes' | 'no' | 'short' | 'long' | 'unknown'
    score: float         # answerability score of the winning chunk (0 if none)
    start: int           # winning span in final-input token coordinates
    end: int
    n_chunks: int
    latency_ms: float

    def to_json(self) -> dict:
        return {
            "answer": self.answer,
            "label": self.label,
            "score": round(float(self.score), 6),
            "start": int(self.start),
            "end": int(self.end),
            "n_chunks": int(self.n_chunks),
            "latency_ms": round(float(self.latency_ms), 3),
        }


@dataclass
class _ChunkRef:
    """Batcher payload: which request, which chunk.

    ``key`` is the chunk's tier-2 cache key when the chunk-result cache is
    enabled and this chunk is the LEADER of a single-flight entry (the row
    computed for it must be published via ``ChunkResultCache.complete`` /
    ``fail_flight``); None otherwise."""

    ticket: "RequestTicket"
    idx: int
    input_ids: List[int]
    key: Optional[str] = None


# request ids key the serving trace spans (admission -> queue -> device ->
# span_reduce -> respond belong to one request across threads); monotonic
# per process, allocated lock-free
_REQUEST_IDS = itertools.count(1)


class RequestTicket:
    """Completion handle for one submitted request."""

    def __init__(self, *, n_chunks: int, question_len: int,
                 request_id: Optional[str] = None):
        # a router-forwarded id (fleet/router.py X-Request-Id) keeps this
        # request's trace spans joinable across the hop; local submissions
        # draw from the engine-wide monotonic counter
        self.request_id = request_id if request_id is not None \
            else next(_REQUEST_IDS)
        self.n_chunks = n_chunks
        # long-request provenance (ISSUE 20): how many dedicated scatter
        # batches this request's chunks launched as; 0 = the ordinary
        # coalescing queue served it
        self.scatter_batches = 0
        self.question_len = question_len
        self.created_at = time.perf_counter()
        self.chunks: List[List[int]] = []
        self._outputs: Dict[int, Tuple] = {}
        self._event = threading.Event()
        self._error: Optional[BaseException] = None
        self._result: Optional[QAResult] = None
        self._lock = threading.Lock()

    def _offer(self, idx: int, row: Dict[str, float]) -> bool:
        """Record one chunk's packed-output row; True when this was the
        last outstanding chunk."""
        with self._lock:
            self._outputs[idx] = row
            return len(self._outputs) == self.n_chunks

    def _fail(self, exc: BaseException) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._error = exc
        self._event.set()

    def _finish(self, result: QAResult) -> None:
        self._result = result
        self._event.set()

    def result(self, timeout: Optional[float] = None) -> QAResult:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request did not complete within {timeout}s "
                f"({len(self._outputs)}/{self.n_chunks} chunks done)"
            )
        if self._error is not None:
            raise self._error
        assert self._result is not None
        return self._result


class QAEngine:
    """Long-running QA serving engine over one model + parameter set."""

    def __init__(
        self,
        model,
        params,
        tokenizer,
        *,
        grid: BucketGrid,
        mesh=None,
        max_batch_delay_ms: float = 10.0,
        queue_size: int = 256,
        max_question_len: int = 64,
        doc_stride: int = 128,
        registry: Optional[Registry] = None,
        quantize: str = "off",
        serve_cache_bytes: int = 0,
        doc_cache_bytes: int = 0,
        long_scatter_chunks: int = 0,
    ):
        self.model = model
        self.params = params
        self.tokenizer = tokenizer
        self.grid = grid
        self.mesh = mesh if mesh is not None else build_mesh()
        # the declarative parallelism plan: every placement below (bucket
        # sharding over data, the replicated small-bucket fallback)
        # derives from it
        self.plan = ParallelPlan.from_mesh(self.mesh)
        self.max_question_len = int(max_question_len)
        self.doc_stride = int(doc_stride)
        # long-request scatter path (ISSUE 20): a request whose document
        # windows into at least this many chunks bypasses deadline
        # coalescing — its chunks launch chunk-parallel as dedicated
        # batches sliced by ``BucketGrid.scatter_plan`` (a whole book
        # answers in one POST /v1/qa call, len(plan) device steps).
        # 0 (default) disables the path.
        self.long_scatter_chunks = int(long_scatter_chunks or 0)
        self._closed = False
        # the ACTIVE serving precision: callers pass 'int8' when the model/
        # params pair came through quant.quantize_model (cli/serve.py wires
        # --quantize straight through); exposed on /metrics and in the
        # warmup report so an operator can tell at a glance what a replica
        # is running
        self.quantize = str(quantize or "off")

        # -- serving hot-path caches (serve/cache.py; both off by default) ----
        # tier 1: document preprocessing (encode_document tokens + the
        # window_chunks layout), keyed by document content hash
        self._doc_cache = (
            ByteBudgetLRU(doc_cache_bytes) if doc_cache_bytes > 0 else None
        )
        # tier 2: per-chunk packed span-logit rows keyed by the exact device
        # input row + checkpoint fingerprint + active precision, with
        # single-flight dedup of identical in-flight chunks
        self._chunk_cache = (
            ChunkResultCache(serve_cache_bytes)
            if serve_cache_bytes > 0 else None
        )
        # the fingerprint device->host copy is only paid when tier 2 can
        # actually use it
        self._fingerprint = (
            params_fingerprint(params)
            if self._chunk_cache is not None else None
        )
        # measured flush ranking (ROADMAP serving front (d)): per-(batch,
        # seq) step-cost estimates are static once warmup records them, so
        # the batcher-thread hook memoizes autotune-cache lookups here
        self._flush_cost_memo: Dict[Tuple[int, int], Optional[float]] = {}
        # AOT program-store dispatch plane (ops/aot.py): per-(batch, seq)
        # bucket executables, populated during warmup (before the batcher
        # thread starts) and read lock-free by _run_batch. Empty when the
        # store is disabled — the hot path then falls back to self._jit,
        # which is exactly the pre-store behavior.
        self._compiled_programs: Dict[Tuple[int, int], object] = {}

        # ids-only wire when the vocab fits uint16 (predictor parity — see
        # infer/score.py for the two wire formats)
        try:
            vocab = len(tokenizer)
        except TypeError:
            vocab = getattr(tokenizer, "vocab_size", 1 << 20)
        self._pad_id = int(tokenizer.pad_token_id)
        self._sep_id = int(tokenizer.sep_token_id)
        self._cls_id = int(tokenizer.cls_token_id)
        self._is_bert = getattr(tokenizer, "model_name", "bert") == "bert"
        self._wire_ids_only = vocab is not None and vocab < 2 ** 16
        if self._wire_ids_only:
            fwd = build_score_fn(
                model, wire_ids_only=True, pad_id=self._pad_id,
                sep_id=self._sep_id, is_bert=self._is_bert,
            )
        else:
            fwd = build_score_fn(model, wire_ids_only=False)
        import jax

        self._jit = jax.jit(fwd)

        # -- metrics plane ---------------------------------------------------
        self.metrics = registry if registry is not None else Registry()
        m = self.metrics
        self.m_requests = m.counter(
            "qa_requests_total", "QA requests admitted.")
        self.m_completed = m.counter(
            "qa_requests_completed_total", "QA requests answered.")
        self.m_failed = m.counter(
            "qa_requests_failed_total", "QA requests failed internally.")
        self.m_rejected_full = m.counter(
            "qa_rejected_queue_full_total",
            "Requests rejected by queue-full backpressure.")
        self.m_rejected_draining = m.counter(
            "qa_rejected_draining_total",
            "Requests rejected while draining for shutdown.")
        self.m_rejected_invalid = m.counter(
            "qa_rejected_invalid_total",
            "Requests rejected as unservable (over-long, empty).")
        self.m_queue_depth = m.gauge(
            "qa_queue_depth", "Chunks waiting in the micro-batch queue.")
        self.m_batches = m.counter(
            "qa_batches_total", "Bucket batches launched.")
        self.m_last_batch_rows = m.gauge(
            "qa_last_batch_rows", "Valid rows in the most recent batch.")
        self.m_occupancy = m.histogram(
            "qa_batch_occupancy",
            "Valid rows / bucket batch rows per launched batch.",
            buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 1.0),
        )
        self.m_padding_waste = m.histogram(
            "qa_padding_waste_ratio",
            "Padded token slots / total token slots per launched batch.",
            buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 1.0),
        )
        self.m_latency = m.histogram(
            "qa_request_latency_seconds",
            "End-to-end request latency (submit to reduced answer).")
        self.m_latency_p50 = m.gauge(
            "qa_request_latency_p50_seconds",
            "p50 request latency over recent requests.")
        self.m_latency_p95 = m.gauge(
            "qa_request_latency_p95_seconds",
            "p95 request latency over recent requests.")
        self.m_latency_p99 = m.gauge(
            "qa_request_latency_p99_seconds",
            "p99 request latency over recent requests.")
        self.m_precision = m.info(
            "qa_active_precision",
            "Numeric precision of the serving forward (int8 = the "
            "post-training quantized path, quant/).",
            {"precision": "int8" if self.quantize == "int8" else "bf16"})
        self.m_weight_bytes = m.gauge(
            "qa_weight_bytes",
            "Resident model parameter bytes (int8 quantization roughly "
            "quarters the float kernels).")
        from ..quant.quantize import param_bytes

        self.m_weight_bytes.set(param_bytes(params))

        # cache series are registered unconditionally (budget 0 included):
        # the /metrics surface must not change shape with configuration, and
        # the docs-consistency gate walks every registered name
        self._cache_metrics = {
            name: {
                "hits": m.counter(
                    f"qa_{name}_cache_hits_total", f"{what} cache hits."),
                "misses": m.counter(
                    f"qa_{name}_cache_misses_total", f"{what} cache misses."),
                "evictions": m.counter(
                    f"qa_{name}_cache_evictions_total",
                    f"{what} cache LRU evictions (byte budget)."),
                "bytes": m.gauge(
                    f"qa_{name}_cache_bytes",
                    f"{what} cache resident bytes (exact accounting)."),
                "entries": m.gauge(
                    f"qa_{name}_cache_entries", f"{what} cache entries."),
            }
            for name, what in (
                ("doc", "Tier-1 document-preprocessing"),
                ("chunk", "Tier-2 chunk-result"),
            )
        }
        # AOT program-store series, mirroring the trainer's
        # train_aot_cache_* plane (registered unconditionally: the
        # /metrics surface must not change shape with configuration)
        self.m_aot_hits = m.counter(
            "qa_aot_cache_hits_total",
            "Bucket programs deserialized from the AOT program store "
            "instead of compiled (ops/aot.py).")
        self.m_aot_misses = m.counter(
            "qa_aot_cache_misses_total",
            "Bucket programs compiled and persisted to the AOT program "
            "store for the next restart.")
        self.m_aot_load = m.histogram(
            "qa_aot_load_seconds",
            "AOT bucket-program load (deserialize) times on store hits.")
        self.m_longdoc_requests = m.counter(
            "qa_longdoc_requests_total",
            "Requests served through the long-request scatter path "
            "(chunk-parallel dedicated batches, ISSUE 20).")
        self.m_longdoc_batches = m.counter(
            "qa_longdoc_scatter_batches_total",
            "Dedicated scatter batches launched for long requests.")
        self.m_flight_joins = m.counter(
            "qa_chunk_flight_joins_total",
            "Chunks that piggybacked on an identical in-flight chunk "
            "(single-flight dedup wins).")
        # mirror bookkeeping for _sync_cache_metrics: last-synced source
        # values per series, under a lock — /metrics renders on concurrent
        # HTTP handler threads, and an unguarded read-modify-write of the
        # counter deltas would double-count under racing scrapes
        self._cache_sync_lock = threading.Lock()
        self._cache_synced: Dict[str, float] = {}

        self.batcher = MicroBatcher(
            grid,
            self._run_batch,
            max_batch_delay_ms=max_batch_delay_ms,
            queue_size=queue_size,
            fail_fn=self._fail_batch,
            on_depth=self.m_queue_depth.set,
            # measured flush ranking needs a cost source: with the autotuner
            # disabled every lookup would return None and the hook would
            # reorder deadline flushes (ascending-seq fallback) with nothing
            # measured behind it — keep the historical oldest-first order
            flush_cost_fn=(
                self._flush_cost if autotune.get().enabled else None),
        )
        self.warmup_report: Optional[dict] = None

    # -- warmup + predict-step HBM pre-flight ---------------------------------

    def _dummy_inputs(self, bucket: Bucket) -> dict:
        """A dense (fully-attended) host batch at the bucket shape:
        [CLS] filler... [SEP] rows, so warmup executes the same program
        shape traffic will."""
        ids = np.full((bucket.batch, bucket.seq), self._cls_id, np.int32)
        ids[:, -1] = self._sep_id
        lengths = np.full((bucket.batch,), bucket.seq, np.int32)
        return self._host_arrays(ids, lengths)

    def _host_arrays(self, ids: np.ndarray, lengths: np.ndarray) -> dict:
        """collate-shaped host dict from an id plane + true row lengths."""
        positions = np.arange(ids.shape[1], dtype=np.int32)[None, :]
        attention_mask = (positions < lengths[:, None]).astype(np.int32)
        token_type_ids = np.zeros_like(ids)
        if self._is_bert:
            for i in range(ids.shape[0]):
                row = ids[i, : lengths[i]]
                seps = np.flatnonzero(row == self._sep_id)
                sep_pos = int(seps[0]) if seps.size else int(lengths[i]) - 1
                token_type_ids[i, sep_pos + 1: lengths[i]] = 1
        return {
            "input_ids": ids,
            "attention_mask": attention_mask,
            "token_type_ids": token_type_ids,
        }

    def _wire_pack(self, inputs: dict):
        """Host dict -> device array in the engine's wire format.

        Bucket batches divisible by the mesh data axis are sharded over it
        (predictor parity); smaller buckets are REPLICATED instead — a
        2-row bucket on an 8-device mesh is a legitimate low-latency
        configuration, and refusing it would force every grid to scale with
        the pod. Warmup logs which placement each bucket got."""
        if self._wire_ids_only:
            packed = np.asarray(inputs["input_ids"], np.uint16)
            batch_axis = 0
        else:
            packed = np.stack(
                [
                    np.asarray(inputs["input_ids"], np.int32),
                    np.asarray(inputs["attention_mask"], np.int32),
                    np.asarray(inputs["token_type_ids"], np.int32),
                ]
            )
            batch_axis = 1
        data_size = self.plan.data_size
        if packed.shape[batch_axis] % max(data_size, 1) == 0:
            if batch_axis == 0:
                return make_global_array(packed, self.mesh)
            return make_global_array(packed, self.mesh, batch_axis=1)
        return self.plan.put_replicated(packed)

    def preflight_predict_step(
        self, bucket: Bucket, *, limit_bytes=None, compile_fn=None,
    ) -> Optional[dict]:
        """Lower + compile one bucket's program and read XLA's
        ``memory_analysis()``; returns ``{"bytes": projected, "limit":
        device_hbm, "fits": bool}`` or None when no limit/analysis is
        available (the planner stands down rather than guessing — CPU tier-1
        exercises the decision through ``compile_fn``/``limit_bytes``
        injection, exactly like ``Trainer.preflight_train_step``)."""
        limit = limit_bytes if limit_bytes is not None else device_hbm_bytes()
        if limit is None:
            return None
        if compile_fn is not None:
            compiled = compile_fn(bucket)
        else:
            with self.mesh:
                dev = self._wire_pack(self._dummy_inputs(bucket))
                compiled = self._aot_bucket_program(
                    bucket.batch, bucket.seq, dev)
        # this compiled program is exactly what the flush-cost recorder
        # needs — record here so warmup doesn't pay a second AOT compile
        # (injected compile_fn fakes expose no cost_analysis: a no-op)
        self._record_program_cost(bucket, compiled)
        try:
            analysis = compiled.memory_analysis()
        except Exception as e:  # noqa: BLE001 - analysis is best-effort
            logger.info("predict pre-flight: memory_analysis unavailable "
                        "(%s); skipping.", e)
            return None
        need = preflight_bytes(analysis)
        if need is None:
            return None
        return {"bytes": int(need), "limit": int(limit),
                "fits": need <= limit}

    def warmup(self, *, hbm_preflight: bool = True, limit_bytes=None,
               compile_fn=None) -> dict:
        """Compile every bucket program up front (startup pays all compiles;
        traffic pays none) and pre-flight each against device HBM, shrinking
        the grid instead of OOMing mid-traffic. Kernel-geometry decisions
        ride the process-wide autotune cache, so a warm restart performs
        zero probes (the report carries the autotuner's session summary)."""
        from ..quant.quantize import param_bytes

        t0 = time.perf_counter()
        report = {
            "buckets": [], "dropped": [], "preflight": {},
            "wire": "ids" if self._wire_ids_only else "3plane",
            # precision provenance: the pre-flight's memory_analysis below
            # already sees the ~4x-smaller int8 kernels (bigger buckets
            # fit), and bench.py surfaces both fields in its JSON line
            "quantize": self.quantize,
            "quant_mem_bytes": param_bytes(self.params),
            # plan topology, mirroring the trainer's HBM pre-flight
            # report: stranded chips are visible, not logged-and-lost
            "mesh_axes": self.plan.describe(),
            "mesh_unused_devices": self.plan.unused_devices,
        }
        for bucket in list(self.grid):
            if hbm_preflight:
                verdict = self.preflight_predict_step(
                    bucket, limit_bytes=limit_bytes, compile_fn=compile_fn,
                )
                if verdict is not None:
                    report["preflight"][str(bucket)] = verdict
                    if not verdict["fits"]:
                        if self.grid.drop(bucket):
                            logger.warning(
                                "predict pre-flight: bucket %s needs %.2f GB "
                                "vs %.2f GB device HBM; dropping it from the "
                                "serving grid.", bucket,
                                verdict["bytes"] / 1e9, verdict["limit"] / 1e9,
                            )
                            report["dropped"].append(str(bucket))
                            continue
                        logger.warning(
                            "predict pre-flight: bucket %s exceeds device "
                            "HBM but is the last bucket; keeping it — XLA "
                            "will decide.", bucket,
                        )
            # execute once at the bucket shape so the dispatch-path cache is
            # hot before traffic arrives
            with self.mesh:
                dev = self._wire_pack(self._dummy_inputs(bucket))
                # measured per-bucket admission (ROADMAP serving front (d)):
                # persist XLA's cost_analysis() estimate of this bucket's
                # whole program so deadline flushes rank by measured step
                # cost. The HBM pre-flight above already recorded it from
                # its own compile when it ran; this AOT compile happens only
                # when no verdict exists yet — a warm restart finds the
                # verdict cached (a no-estimate marker included) and skips
                # it entirely (zero-probe startup intact: record_cost never
                # touches the probe counters).
                tuner = autotune.get()
                est = tuner.lookup_cost(
                    self._program_cost_key(bucket.batch, bucket.seq))
                program = (
                    self._aot_bucket_program(bucket.batch, bucket.seq, dev)
                    if aot.get().enabled else None
                )
                if tuner.enabled and est is None:
                    # store disabled: load_or_compile degrades to the same
                    # lower+compile this site always paid (bypass outcome)
                    est = self._record_program_cost(
                        bucket,
                        program if program is not None
                        else aot.get().load_or_compile(
                            "serve-step", self._jit, self.params, dev,
                            geometry=f"{bucket.batch}x{bucket.seq}",
                            plan=aot.plan_signature(self.plan),
                            extra=self._program_signature(),
                        ))
                np.asarray(
                    program(self.params, dev) if program is not None
                    else self._jit(self.params, dev))
            report.setdefault("program_costs", {})[str(bucket)] = (
                est["est_seconds"] if est else None)
            report["buckets"].append(str(bucket))
        report["autotune"] = autotune.get().session_summary()
        report["aot"] = aot.get().session_summary()
        report["warmup_seconds"] = round(time.perf_counter() - t0, 3)
        self.warmup_report = report
        self.batcher.start()
        logger.info(
            "serving warmup: %d bucket programs compiled (%s dropped by "
            "pre-flight) in %.1fs; autotune probes this session: %d.",
            len(report["buckets"]), len(report["dropped"]) or "none",
            report["warmup_seconds"], report["autotune"]["probes"],
        )
        return report

    # -- measured flush ranking (batcher thread) -------------------------------

    def _program_signature(self) -> str:
        """Shape-independent identity of the serving program: model
        geometry (caches are shared per device kind — bert-tiny's
        artifacts must never serve a bert-large grid), wire format, and
        active precision (the ``q8`` suffix discipline of
        ops/quant_matmul.py). Shared between the autotune cost keys and
        the AOT program-store keys so both planes agree on what "the same
        program" means."""
        cfg = getattr(self.model, "cfg", None)
        sig = (
            f"h{cfg.hidden_size}l{cfg.num_layers}n{cfg.num_heads}"
            f"v{cfg.vocab_size}" if cfg is not None else "anon"
        )
        wire = "ids" if self._wire_ids_only else "3p"
        suffix = "-q8" if self.quantize == "int8" else ""
        return f"{sig}-{wire}{suffix}"

    def _program_cost_key(self, batch: int, seq: int) -> str:
        """Tuning-cache key of one bucket's whole serving program: each
        (shape, signature) pair is a different compiled program with a
        different measured cost."""
        return f"serve-step-{batch}x{seq}-{self._program_signature()}"

    def _aot_bucket_program(self, batch: int, seq: int, dev):
        """One bucket's executable through the AOT program store
        (ops/aot.py): deserialized on a warm restart (zero XLA compiles),
        compiled-and-persisted on a cold one. Memoized per (batch, seq)
        for the batcher-thread hot path; bypass outcomes (store disabled)
        are NOT memoized — the engine then dispatches through self._jit
        exactly as before the store existed."""
        key = (batch, seq)
        program = self._compiled_programs.get(key)
        if program is not None:
            return program
        program, outcome, seconds = aot.get().load_or_compile_ex(
            "serve-step", self._jit, self.params, dev,
            geometry=f"{batch}x{seq}",
            plan=aot.plan_signature(self.plan),
            extra=self._program_signature(),
        )
        if outcome != "bypass":
            self._compiled_programs[key] = program
            if outcome == "hit":
                self.m_aot_hits.inc()
                self.m_aot_load.observe(seconds)
            else:
                self.m_aot_misses.inc()
        return program

    def _record_program_cost(self, bucket: Bucket, compiled) -> Optional[dict]:
        """Persist ``compiled``'s ``cost_analysis()`` estimate for this
        bucket's program in the autotune cache (flush ranking reads it
        back), unless one is already cached. Returns the estimate in
        effect, or None when the toolchain exposes none."""
        tuner = autotune.get()
        if not tuner.enabled:
            return None
        key = self._program_cost_key(bucket.batch, bucket.seq)
        cached = tuner.lookup_cost(key)
        if cached is not None and cached.get("est_seconds") is not None:
            return cached
        est = autotune.program_cost_estimate(compiled)
        # persist even a no-estimate verdict ({"est_seconds": None}): the
        # cost-probe compile must be paid once per cache lifetime, not once
        # per startup on toolchains whose cost_analysis yields nothing —
        # and a free compile (preflight already has one) may upgrade a
        # stale no-estimate marker
        tuner.record_cost(
            key, est if est is not None else {"est_seconds": None})
        return est

    def _flush_cost(self, seq: int, n: int) -> Optional[float]:
        """Estimated step cost of the program a deadline flush of ``n``
        items at ``seq`` would launch, from the autotune cache's persisted
        ``cost_analysis()`` verdicts (None -> the batcher falls back to
        seq order). Memoized: the hook runs under the batcher lock and the
        estimates are static after warmup."""
        batch = self.grid.batch_for(seq, n)
        memo_key = (batch, seq)
        if memo_key not in self._flush_cost_memo:
            est = autotune.get().lookup_cost(
                self._program_cost_key(batch, seq))
            self._flush_cost_memo[memo_key] = (
                float(est["est_seconds"])
                if est and est.get("est_seconds") is not None else None)
        return self._flush_cost_memo[memo_key]

    # -- request admission -----------------------------------------------------

    def _chunk_document(self, document: str, question_len: int) -> List:
        """``encode_document`` + ``window_chunks`` for one request, through
        the tier-1 cache when enabled.

        Two entry kinds share the byte budget: the token stream keyed by
        document content hash alone (question-independent — the same
        document asked a hundred different questions of tokenizes once),
        and the window layout keyed additionally by the question LENGTH +
        grid geometry (the only question-dependence ``window_chunks`` has:
        ``document_len = max_seq - question_len - 3``)."""
        max_seq = self.grid.max_seq

        def chunk(tokens):
            # spanless target: serving has no gold answer; the chunker only
            # needs geometry
            return window_chunks(
                tokens, ("unknown", -1, -1),
                question_len=question_len, max_seq_len=max_seq,
                doc_stride=self.doc_stride,
            )

        if self._doc_cache is None:
            tokens, _, _ = encode_document(self.tokenizer, document)
            return chunk(tokens)

        doc_hash = content_key(document)
        win_key = (f"win|{doc_hash}|q{question_len}|s{max_seq}"
                   f"|d{self.doc_stride}")
        records = self._doc_cache.get(win_key)
        if records is not None:
            return records
        tok_key = f"tok|{doc_hash}"
        tokens = self._doc_cache.get(tok_key)
        if tokens is None:
            tokens, _, _ = encode_document(self.tokenizer, document)
            self._doc_cache.put(
                tok_key, tokens,
                ENTRY_OVERHEAD + len(tok_key) + len(tokens) * TOKEN_BYTES,
            )
        records = chunk(tokens)
        cost = ENTRY_OVERHEAD + len(win_key) + sum(
            (len(r.token_ids) + 4) * TOKEN_BYTES for r in records
        )
        self._doc_cache.put(win_key, records, cost)
        return records

    def submit(self, question: str, document: str,
               request_id: Optional[str] = None) -> RequestTicket:
        """Chunk + admit one request; returns a completion ticket.

        ``request_id`` overrides the engine-local id (the fleet router
        forwards its own so per-hop latency joins on one key).

        Raises :class:`RequestRejected` (client error),
        :class:`QueueFullError` (backpressure) or :class:`DrainingError`
        (shutting down)."""
        tracer = trace_mod.current()
        if tracer is None:
            return self._submit(question, document, request_id)
        t0 = tracer.now()
        ticket = self._submit(question, document, request_id)
        tracer.complete(
            "admission", t0, tracer.now(), cat="serve",
            args={"request_id": ticket.request_id,
                  "n_chunks": ticket.n_chunks},
        )
        return ticket

    def _submit(self, question: str, document: str,
                request_id: Optional[str] = None) -> RequestTicket:
        if self._closed:
            self.m_rejected_draining.inc()
            raise DrainingError("engine is shut down")
        if not question or not document:
            self.m_rejected_invalid.inc()
            raise RequestRejected("question and document must be non-empty")

        # fast-fail under overload: when no request could possibly be
        # admitted right now, reject BEFORE paying host-side tokenization
        # and chunking (a saturated server must not burn CPU on requests it
        # then 429s). submit_many below stays the authoritative
        # all-or-nothing check. With the chunk-result cache enabled only
        # the draining arm applies: a fully-hot request needs zero queue
        # slots, so pre-rejecting on depth would 429 exactly the traffic
        # the cache exists to serve.
        try:
            self.batcher.precheck(check_full=self._chunk_cache is None)
        except QueueFullError:
            self.m_rejected_full.inc()
            raise
        except DrainingError:
            self.m_rejected_draining.inc()
            raise

        max_seq = self.grid.max_seq
        enc_q = self.tokenizer.encode(question)[: self.max_question_len]
        if len(enc_q) + 3 >= max_seq:
            self.m_rejected_invalid.inc()
            raise RequestRejected(
                f"question tokenizes to {len(enc_q)} tokens; the largest "
                f"serving bucket ({max_seq}) leaves no room for a document"
            )
        records = self._chunk_document(document, len(enc_q))
        if self._chunk_cache is None and \
                len(records) > self.batcher.queue_size:
            # more chunks than the queue can EVER hold: admission would
            # reject this request on an idle server too, so 429-and-retry
            # would loop forever — fail it as a client error up front,
            # before paying per-chunk assembly. With the chunk cache
            # enabled only MISS chunks need queue slots, so the same bound
            # applies to the leader count after classification instead
            self.m_rejected_invalid.inc()
            raise RequestRejected(
                f"document chunks into {len(records)} windows, beyond the "
                f"work queue's total capacity ({self.batcher.queue_size}); "
                f"split the document or raise queue_size"
            )

        ticket = RequestTicket(
            n_chunks=len(records), question_len=len(enc_q),
            request_id=request_id)
        rows: List[Tuple[int, int, List[int]]] = []
        for idx, rec in enumerate(records):
            input_ids = assemble_input_ids(
                self._cls_id, self._sep_id, enc_q, rec)
            seq = self.grid.admit(len(input_ids))
            if seq is None:  # unreachable with window_chunks at max_seq,
                # kept as a hard error: an unadmittable chunk must never
                # reach the compile path
                self.m_rejected_invalid.inc()
                raise RequestRejected(
                    f"chunk of {len(input_ids)} tokens exceeds every "
                    f"serving bucket (max {max_seq})"
                )
            ticket.chunks.append(input_ids)
            rows.append((idx, seq, input_ids))

        cache = self._chunk_cache
        if cache is None:
            works = [
                ChunkWork(seq=seq, payload=_ChunkRef(ticket, idx, input_ids))
                for idx, seq, input_ids in rows
            ]
            try:
                self._admit_works(ticket, works)
            except QueueFullError:
                self.m_rejected_full.inc()
                raise
            except DrainingError:
                self.m_rejected_draining.inc()
                raise
            self.m_requests.inc()
            return ticket

        # tier-2 classify-and-admit, atomic under the cache lock: each chunk
        # is a HIT (row served from the LRU, bypassing the batcher), a
        # WAITER (identical row already in flight — piggyback, single-flight
        # dedup), or a LEADER (leased flight; must reach the queue or be
        # aborted under this same lock hold, so no thread can join a flight
        # that never launches).
        hits: List[Tuple[int, Dict[str, float]]] = []
        works = []
        leased: List[str] = []
        # key hashing depends only on immutable inputs — do it OUTSIDE the
        # cache lock so a many-window document doesn't serialize every other
        # handler thread's admission and the batcher's result publication
        keyed = [
            (idx, seq, input_ids,
             row_key(self._fingerprint, self.quantize, input_ids))
            for idx, seq, input_ids in rows
        ]
        with cache.lock:
            for idx, seq, input_ids, key in keyed:
                row = cache.get(key)
                if row is not None:
                    hits.append((idx, row))
                    continue
                if cache.join_flight(key, (ticket, idx)):
                    continue
                leased.append(key)
                works.append(ChunkWork(
                    seq=seq,
                    payload=_ChunkRef(ticket, idx, input_ids, key=key)))

            def rollback():
                # atomic rollback: drop our waiter registrations first
                # (from other leaders' flights AND our own leased ones,
                # so every undone join lands in flight_join_rollbacks),
                # then forget the leased flights (no foreign waiter can
                # have joined them — we still hold the lock)
                cache.remove_waiters(ticket)
                for key in leased:
                    cache.abort_flight(key)

            if len(works) > self.batcher.queue_size:
                # only MISS chunks need queue slots; more of them than the
                # queue can EVER hold is a permanent client error (the
                # no-cache path rejects this shape before assembly)
                rollback()
                self.m_rejected_invalid.inc()
                raise RequestRejected(
                    f"document needs {len(works)} uncached windows, beyond "
                    f"the work queue's total capacity "
                    f"({self.batcher.queue_size}); split the document or "
                    f"raise queue_size"
                )
            if works:
                try:
                    self._admit_works(ticket, works)
                except (QueueFullError, DrainingError) as exc:
                    rollback()
                    if isinstance(exc, QueueFullError):
                        self.m_rejected_full.inc()
                    else:
                        self.m_rejected_draining.inc()
                    raise
        self.m_requests.inc()
        # hit rows flow to the ticket only after admission succeeded (a
        # rejected request must leave no partial state); a fully-hot request
        # finalizes right here on the handler thread — it never touches the
        # batcher, the queue, or the device
        done = False
        for idx, row in hits:
            if ticket._offer(idx, row):
                done = True
        if done:
            self._finalize(ticket)
        return ticket

    def _admit_works(self, ticket: RequestTicket, works: List) -> None:
        """Queue one request's chunk works: the coalescing queue normally,
        or — when the request windows into at least ``long_scatter_chunks``
        chunks — the long-request scatter path: per-seq slices from
        ``BucketGrid.scatter_plan`` submitted as dedicated batches that
        launch immediately and back-to-back (``MicroBatcher.submit_group``).
        Raises exactly what ``submit_many`` raises; on rejection nothing is
        queued (the group admission is all-or-nothing too)."""
        if not self.long_scatter_chunks or \
                len(works) < self.long_scatter_chunks:
            self.batcher.submit_many(works)
            return
        by_seq: Dict[int, List] = {}
        for w in works:
            by_seq.setdefault(w.seq, []).append(w)
        slices = []
        for seq in sorted(by_seq):
            ws = by_seq[seq]
            for batch in self.grid.scatter_plan(seq, len(ws)):
                slices.append(ws[:batch])
                ws = ws[batch:]
        self.batcher.submit_group(slices)
        ticket.scatter_batches = len(slices)
        self.m_longdoc_requests.inc()
        self.m_longdoc_batches.inc(len(slices))

    # -- batch execution (batcher thread) --------------------------------------

    def _run_batch(self, seq: int, works: Sequence[ChunkWork]) -> None:
        n = len(works)
        batch = self.grid.batch_for(seq, n)

        tracer = trace_mod.current()
        t_flush0 = time.perf_counter()
        if tracer is not None:
            # per-chunk queue-wait spans: enqueued_at is a monotonic stamp,
            # so map the WAIT duration onto the tracer clock ending now
            waited_now = time.monotonic()
            for w in works:
                if w.enqueued_at:
                    wait = max(0.0, waited_now - w.enqueued_at)
                    tracer.complete(
                        "queue", t_flush0 - wait, t_flush0, cat="serve",
                        args={"request_id": w.payload.ticket.request_id},
                    )

        ids = np.full((n, seq), self._pad_id, np.int32)
        lengths = np.empty((n,), np.int32)
        for i, w in enumerate(works):
            row = w.payload.input_ids
            ids[i, : len(row)] = row
            lengths[i] = len(row)
        if self._wire_ids_only:
            # mask and token types are derived in-jit from the id plane
            # (infer/score.py); building them host-side would be wasted
            # per-batch work
            inputs = {"input_ids": ids}
        else:
            inputs = self._host_arrays(ids, lengths)
        inputs = pad_trailing_batch(inputs, batch)

        t_dev0 = time.perf_counter()
        with self.mesh:
            dev = self._wire_pack(inputs)
            # warmup populated the store-backed dispatch plane for every
            # surviving bucket; an empty memo (store disabled) or an
            # unwarmed shape falls back to the jit dispatch cache
            program = self._compiled_programs.get((batch, seq))
            out = np.asarray(
                (program if program is not None else self._jit)(
                    self.params, dev))[:, :n]
        if tracer is not None:
            tracer.complete(
                "device", t_dev0, time.perf_counter(), cat="serve",
                args={"seq": seq, "rows": n, "batch": batch},
            )

        self.m_batches.inc()
        self.m_last_batch_rows.set(n)
        self.m_occupancy.observe(n / batch)
        self.m_padding_waste.observe(
            1.0 - float(lengths.sum()) / float(batch * seq))

        decoded = {k: out[i] for i, k in enumerate(OUT_KEYS)}
        cache = self._chunk_cache
        for i, w in enumerate(works):
            ref: _ChunkRef = w.payload
            row = {k: float(decoded[k][i]) for k in OUT_KEYS}
            offers = [(ref.ticket, ref.idx)]
            if cache is not None and ref.key is not None:
                # publish the leader's row: cache it for future requests and
                # release every single-flight waiter with the SAME object —
                # cached and computed responses are bit-identical by
                # construction
                waiters, _ = cache.complete(
                    ref.key, row,
                    ENTRY_OVERHEAD + len(ref.key) + 8 * len(OUT_KEYS),
                )
                offers.extend(waiters)
            for ticket, idx in offers:
                if ticket._offer(idx, row):
                    self._finalize(ticket)
        if tracer is not None:
            tracer.complete(
                "flush", t_flush0, time.perf_counter(), cat="serve",
                args={"seq": seq, "rows": n},
            )

    def _fail_batch(self, works: Sequence[ChunkWork], exc: BaseException) -> None:
        cache = self._chunk_cache
        failed = set()

        def fail(ticket: RequestTicket) -> None:
            if id(ticket) not in failed:
                failed.add(id(ticket))
                ticket._fail(exc)

        for w in works:
            fail(w.payload.ticket)
            if cache is not None and w.payload.key is not None:
                # single-flight waiters were promised this leader's row;
                # nothing is cached and their tickets fail with it
                for ticket, _ in cache.fail_flight(w.payload.key):
                    fail(ticket)
        self.m_failed.inc(len(failed))

    # -- reduction (predictor.py:63-87 parity) ---------------------------------

    def _finalize(self, ticket: RequestTicket) -> None:
        """Reduce chunk outputs to the per-request best span, applying the
        predictor's validity rules in chunk order (ties resolve to the
        later chunk, exactly as the predictor's sequential stream does)."""
        with trace_mod.span("span_reduce", cat="serve",
                            args={"request_id": ticket.request_id,
                                  "n_chunks": ticket.n_chunks}):
            self._finalize_inner(ticket)

    def _finalize_inner(self, ticket: RequestTicket) -> None:
        best_score = 0.0   # predictor: defaultdict(int) floor of 0
        best: Optional[Tuple[int, dict]] = None
        for idx in range(ticket.n_chunks):
            row = ticket._outputs[idx]
            start_id = int(row["start_ids"])
            end_id = int(row["end_ids"])
            score = row["scores"]
            if start_id > end_id:
                continue
            # answer must not start inside "[CLS] question [SEP]"
            if start_id < ticket.question_len + 2:
                continue
            if best_score > score:
                continue
            best_score = score
            best = (idx, row)

        latency = time.perf_counter() - ticket.created_at
        if best is None:
            result = QAResult(
                answer="", label="unknown", score=0.0, start=-1, end=-1,
                n_chunks=ticket.n_chunks, latency_ms=latency * 1e3,
            )
        else:
            idx, row = best
            start_id = int(row["start_ids"])
            end_id = int(row["end_ids"])
            label = RawPreprocessor.id2labels[int(row["labels"])]
            if label in ("yes", "no"):
                answer = label
            elif label == "unknown":
                answer = ""
            else:
                span = ticket.chunks[idx][start_id: end_id + 1]
                answer = self.tokenizer.decode(span)
            result = QAResult(
                answer=answer, label=label, score=float(row["scores"]),
                start=start_id, end=end_id, n_chunks=ticket.n_chunks,
                latency_ms=latency * 1e3,
            )
        self.m_completed.inc()
        self.m_latency.observe(latency)
        ticket._finish(result)

    # -- metrics / shutdown ----------------------------------------------------

    def cache_stats(self) -> dict:
        """Both tiers' live stats (None for a disabled tier) — the bench
        JSON line and /metrics mirroring read this one surface."""
        out = {"doc": None, "chunk": None}
        if self._doc_cache is not None:
            out["doc"] = self._doc_cache.stats()
        if self._chunk_cache is not None:
            out["chunk"] = self._chunk_cache.stats()
            out["chunk"]["flight_joins"] = self._chunk_cache.flight_joins
            out["chunk"]["flight_join_rollbacks"] = (
                self._chunk_cache.flight_join_rollbacks)
            out["chunk"]["inflight"] = self._chunk_cache.inflight()
        return out

    def _sync_cache_metrics(self) -> None:
        """Mirror the caches' own monotonic stats into the Prometheus
        series. The whole read-delta-inc runs under one lock with a
        last-synced snapshot (NOT a read-back of the counter): /metrics
        renders on concurrent HTTP handler threads, and two racing scrapes
        computing the same delta would otherwise double-count. Rollback
        corners may briefly move a source stat backwards, hence the max."""
        stats = self.cache_stats()
        with self._cache_sync_lock:
            for name, s in stats.items():
                if s is None:
                    continue
                mm = self._cache_metrics[name]
                for k in ("hits", "misses", "evictions"):
                    last = self._cache_synced.setdefault(f"{name}.{k}", 0.0)
                    mm[k].inc(max(0.0, s[k] - last))
                    self._cache_synced[f"{name}.{k}"] = max(last, float(s[k]))
                mm["bytes"].set(s["bytes"])
                mm["entries"].set(s["entries"])
            if stats["chunk"] is not None:
                last = self._cache_synced.setdefault("flight_joins", 0.0)
                joins = float(stats["chunk"]["flight_joins"])
                self.m_flight_joins.inc(max(0.0, joins - last))
                self._cache_synced["flight_joins"] = max(last, joins)

    def render_metrics(self) -> str:
        for gauge, q in ((self.m_latency_p50, 0.5),
                         (self.m_latency_p95, 0.95),
                         (self.m_latency_p99, 0.99)):
            v = self.m_latency.quantile(q)
            if v is not None:
                gauge.set(v)
        self._sync_cache_metrics()
        return self.metrics.render()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admissions, flush every admitted request to completion."""
        self._closed = True
        ok = self.batcher.drain(timeout=timeout)
        return ok

    def close(self, timeout: float = 30.0) -> None:
        self._closed = True
        self.batcher.close(timeout=timeout)
