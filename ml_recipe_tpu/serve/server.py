"""stdlib-only HTTP front end for the QA serving engine.

Endpoints:

- ``POST /v1/qa`` — body ``{"question": ..., "document": ...}``; answers
  ``200 {"answer", "label", "score", ...}``. Backpressure maps to status
  codes: ``429`` queue-full (bounded queue, explicit reject-on-full),
  ``503`` draining/shutdown, ``400`` unservable request, ``504`` deadline.
- ``GET /healthz`` — ``{"status": "ok" | "draining"}`` (ready/liveness).
- ``GET /metrics`` — Prometheus text format (latency histogram +
  p50/p95/p99 gauges, queue depth, batch occupancy, padding waste).

Shutdown composes with the PR-1 supervisor conventions: SIGTERM (and
SIGINT) triggers a DRAIN — admissions stop with clean 503s, every admitted
request is flushed through normal batch launches to a real response, then
the listener closes and the process exits 0. No request that got a 200
admission is ever dropped on the floor.

Threading: ``ThreadingHTTPServer`` handler threads block on their own
request's completion ticket; device batches are serialized on the batcher
thread. An in-flight handler counter lets the drain path wait until the
last response byte is written before the process exits.
"""

from __future__ import annotations

import json
import logging
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..metrics import trace as trace_mod
from ..resilience import faults
from .batcher import DrainingError, QueueFullError
from .engine import QAEngine, RequestRejected

logger = logging.getLogger(__name__)

_MAX_BODY_BYTES = 4 << 20  # 4 MB of JSON is far beyond any bucketable doc


class _QAHandler(BaseHTTPRequestHandler):
    # the default HTTP/1.0 would close the connection per request and make
    # client keep-alive benches meaningless
    protocol_version = "HTTP/1.1"

    server: "_QAHTTPServer"

    # -- plumbing --------------------------------------------------------------

    def log_message(self, fmt, *args):  # quiet stderr; route to logging
        logger.debug("%s - %s", self.address_string(), fmt % args)

    def _send_json(self, code: int, payload: dict, *, extra_headers=()) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str, content_type: str) -> None:
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- routes ----------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        if self.path == "/healthz":
            status = "draining" if self.server.draining else "ok"
            engine = self.server.engine
            self._send_json(200, {
                "status": status,
                "buckets": [str(b) for b in engine.grid],
                # queue pressure for the fleet router's health-driven
                # shedding (fleet/router.py polls this instead of parsing
                # the full /metrics page)
                "queue_depth": int(engine.m_queue_depth.value),
                "queue_limit": int(engine.batcher.queue_size),
            })
        elif self.path == "/metrics":
            self._send_text(
                200, self.server.engine.render_metrics(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        else:
            self._send_json(404, {"error": f"no route {self.path!r}"})

    def _read_body(self) -> bytes:
        """Read the request body, or None-equivalent sentinel on a missing/
        oversized Content-Length. ALWAYS consumes (or kills) the body on a
        keep-alive connection: replying without reading it would leave the
        bytes in the stream to be parsed as the next request line."""
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = 0
        if length <= 0 or length > _MAX_BODY_BYTES:
            self.close_connection = True  # can't safely skip an unknown body
            return b""
        return self.rfile.read(length)

    def do_POST(self) -> None:  # noqa: N802 - http.server API
        body = self._read_body()
        if self.path != "/v1/qa":
            self._send_json(404, {"error": f"no route {self.path!r}"})
            return
        if self.server.draining:
            self._send_json(503, {"error": "draining"})
            return
        if not body:
            self._send_json(400, {"error": "missing or oversized body"})
            return
        try:
            payload = json.loads(body)
            question = payload["question"]
            document = payload["document"]
        except (ValueError, KeyError, TypeError):
            self._send_json(
                400, {"error": 'body must be {"question": ..., "document": ...}'}
            )
            return

        # fleet chaos site: 'fleet.engine:kill@N' (resilience/faults.py)
        # kills this engine process on its Nth admitted request — the
        # drill that proves the router ejects a dead engine mid-load
        faults.fire("fleet.engine")

        # a fleet-router hop forwards its own request id; threading it
        # through the ticket keeps the trace spans joinable across hops
        request_id = self.headers.get("X-Request-Id") or None

        # the 200 send happens INSIDE the in-flight window: the drain path
        # waits on this counter, so decrementing before the response bytes
        # are written would let the process exit mid-write
        self.server.handler_began()
        try:
            ticket = self.server.engine.submit(
                question, document, request_id=request_id)
            # 'respond' span: admission done -> response bytes written (the
            # handler-side wait the client actually experiences)
            with trace_mod.span(
                "respond", cat="serve",
                args={"request_id": ticket.request_id},
            ):
                result = ticket.result(timeout=self.server.request_timeout_s)
                payload = result.to_json()
                payload["request_id"] = ticket.request_id
                self._send_json(200, payload)
        except QueueFullError as e:
            self._send_json(
                429, {"error": f"queue full: {e}"},
                extra_headers=(("Retry-After", "1"),),
            )
        except DrainingError:
            self._send_json(503, {"error": "draining"})
        except RequestRejected as e:
            self._send_json(400, {"error": str(e)})
        except TimeoutError as e:
            self._send_json(504, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 - a request must get SOME answer
            logger.exception("request failed")
            try:
                self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
            except OSError:  # client already gone mid-write
                self.close_connection = True
        finally:
            self.server.handler_done()


class _QAHTTPServer(ThreadingHTTPServer):
    # a wedged client connection must not block process exit; drain
    # correctness is handled by the in-flight handler counter instead
    daemon_threads = True
    engine: QAEngine
    draining: bool
    request_timeout_s: float

    def __init__(self, addr, engine: QAEngine, request_timeout_s: float):
        super().__init__(addr, _QAHandler)
        self.engine = engine
        self.draining = False
        self.request_timeout_s = request_timeout_s
        self._active = 0
        self._active_cv = threading.Condition()

    def handler_began(self) -> None:
        with self._active_cv:
            self._active += 1

    def handler_done(self) -> None:
        with self._active_cv:
            self._active -= 1
            self._active_cv.notify_all()

    def wait_idle(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self._active_cv:
            while self._active > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._active_cv.wait(remaining)
        return True


class QAServer:
    """Engine + HTTP listener + SIGTERM drain, as one runnable unit."""

    def __init__(
        self,
        engine: QAEngine,
        *,
        host: str = "127.0.0.1",
        port: int = 8080,
        request_timeout_s: float = 60.0,
        drain_timeout_s: float = 30.0,
    ):
        self.engine = engine
        self.drain_timeout_s = drain_timeout_s
        self._httpd = _QAHTTPServer((host, port), engine, request_timeout_s)
        self._serve_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        """Serve in a background thread (tests; the CLI uses run_forever)."""
        if self._serve_thread is not None:
            return
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, name="serve-http", daemon=True
        )
        self._serve_thread.start()
        logger.info("serving QA on http://%s:%d (buckets: %s)",
                    self.host, self.port,
                    ",".join(str(b) for b in self.engine.grid))

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT -> drain-and-exit (supervisor-friendly: the PR-1
        supervisor forwards SIGTERM to its child and expects it to stand
        down cleanly)."""
        def _on_signal(signum, frame):  # noqa: ARG001 - signal API
            logger.info("received %s; draining", signal.Signals(signum).name)
            # flip the admission gate HERE, not in shutdown(): from the
            # signal instant every new POST gets a clean 503 while requests
            # admitted before it flush to real answers
            self._httpd.draining = True
            self._stop.set()

        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)

    def shutdown(self) -> None:
        """Drain in-flight + queued work, answer it, then close the listener.

        Order matters: (1) stop admitting (new POSTs get 503 immediately),
        (2) flush the engine queue so every admitted ticket completes,
        (3) wait for handler threads to write their last response bytes,
        (4) stop the accept loop and close the socket.
        """
        self._httpd.draining = True
        self.engine.drain(timeout=self.drain_timeout_s)
        if not self._httpd.wait_idle(self.drain_timeout_s):
            logger.warning(
                "drain: handler threads still active after %.0fs; exiting "
                "anyway", self.drain_timeout_s,
            )
        self.engine.close()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5.0)
            self._serve_thread = None
        logger.info("drain complete; listener closed")

    def wait(self) -> None:
        """Block until a signal (or .stop()) requests shutdown."""
        while not self._stop.wait(0.2):
            pass

    def run_forever(self) -> None:
        """Start, then block until a signal (or .stop()) triggers the drain.
        Returns after a clean drain so the caller can exit 0."""
        self.install_signal_handlers()
        self.start()
        try:
            self.wait()
        finally:
            self.shutdown()

    def stop(self) -> None:
        self._stop.set()
