"""Bucketed shape admission for online serving.

On XLA every novel ``(batch, seq)`` input shape is a fresh multi-second
compile — fatal in a request path. The serving subsystem therefore admits
every request into a SMALL FIXED GRID of pre-compiled ``(batch, seq)``
buckets: a chunk of length L runs in the smallest bucket seq >= L (padded to
it), and a group of N concurrent chunks runs at the smallest bucket batch
>= N (rows padded to it). The whole traffic distribution is served by
``len(grid)`` long-lived compiled programs, all warmed at startup
(``QAEngine.warmup``) so steady-state traffic never compiles.

Also home to ``pad_trailing_batch`` — the pad-rows-to-static-batch helper
factored out of ``infer/predictor.py``'s trailing-partial-batch handling
(the batch predictor and the serving engine pad identically; the regression
test in tests/test_predictor.py pins the bit-identical behavior).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

# "8x128, 16x384" — batch x seq, comma-separated
_BUCKET_RE = re.compile(r"^\s*(\d+)\s*[xX*]\s*(\d+)\s*$")


@dataclass(frozen=True, order=True)
class Bucket:
    """One pre-compiled program shape: ``batch`` rows of ``seq`` tokens."""

    seq: int
    batch: int

    def __str__(self) -> str:  # the spec syntax round-trips
        return f"{self.batch}x{self.seq}"


def parse_bucket_spec(spec: str) -> List[Bucket]:
    """Parse ``"4x64,8x64,8x384"`` (``batch x seq``) into sorted buckets."""
    buckets = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        m = _BUCKET_RE.match(part)
        if not m:
            raise ValueError(
                f"bad bucket {part!r} in spec {spec!r} "
                f"(want 'BATCHxSEQ[,BATCHxSEQ...]', e.g. '8x128,16x384')"
            )
        batch, seq = int(m.group(1)), int(m.group(2))
        if batch < 1 or seq < 8:
            raise ValueError(
                f"bucket {part!r}: batch must be >= 1 and seq >= 8"
            )
        buckets.append(Bucket(seq=seq, batch=batch))
    if not buckets:
        raise ValueError(f"bucket spec {spec!r} names no buckets")
    return sorted(set(buckets))


class BucketGrid:
    """The admission map over a fixed set of ``(batch, seq)`` buckets."""

    def __init__(self, buckets: Sequence[Bucket]):
        if not buckets:
            raise ValueError("empty bucket grid")
        self._by_seq: Dict[int, List[int]] = {}
        for b in sorted(set(buckets)):
            self._by_seq.setdefault(b.seq, []).append(b.batch)
        for batches in self._by_seq.values():
            batches.sort()

    @classmethod
    def from_spec(cls, spec: str) -> "BucketGrid":
        return cls(parse_bucket_spec(spec))

    def __len__(self) -> int:
        return sum(len(bs) for bs in self._by_seq.values())

    def __iter__(self):
        for seq in sorted(self._by_seq):
            for batch in self._by_seq[seq]:
                yield Bucket(seq=seq, batch=batch)

    @property
    def seqs(self) -> List[int]:
        return sorted(self._by_seq)

    @property
    def max_seq(self) -> int:
        return max(self._by_seq)

    def admit(self, seq_len: int) -> Optional[int]:
        """Smallest bucket seq that fits ``seq_len`` tokens, or None when
        the input exceeds every bucket (the caller rejects the request —
        an over-long chunk must never trigger a fresh compile)."""
        for seq in sorted(self._by_seq):
            if seq_len <= seq:
                return seq
        return None

    def batches_for(self, seq: int) -> List[int]:
        return list(self._by_seq[seq])

    def max_batch_for(self, seq: int) -> int:
        return self._by_seq[seq][-1]

    def batch_for(self, seq: int, n_items: int) -> int:
        """Smallest bucket batch >= ``n_items`` at this seq (least padding);
        the largest when even it is smaller than ``n_items`` (the caller
        splits the group)."""
        for batch in self._by_seq[seq]:
            if n_items <= batch:
                return batch
        return self._by_seq[seq][-1]

    def scatter_plan(self, seq: int, n_items: int) -> List[int]:
        """Batch sizes that run ``n_items`` chunks CHUNK-PARALLEL at
        ``seq`` in as few program launches as possible: greedy slices of
        the largest bucket batch, with the remainder admitted into the
        smallest batch that fits it (least padding). This is the
        long-request path (ISSUE 20): a document that windows into dozens
        of chunks launches ``len(plan)`` dedicated batches immediately
        instead of trickling through deadline coalescing one bucket at a
        time — with a long-request bucket sized to the windowed chunk
        count, a whole book answers in ONE device step."""
        if n_items < 1:
            return []
        largest = self.max_batch_for(seq)
        plan = [largest] * (n_items // largest)
        rest = n_items % largest
        if rest:
            plan.append(self.batch_for(seq, rest))
        return plan

    def drop(self, bucket: Bucket) -> bool:
        """Remove one bucket (HBM pre-flight shrinking an over-committed
        grid at warmup instead of OOMing mid-traffic). Returns False when
        it was the last bucket at any seq AND the last seq — the grid never
        shrinks to nothing."""
        batches = self._by_seq.get(bucket.seq)
        if not batches or bucket.batch not in batches:
            return False
        if len(self._by_seq) == 1 and len(batches) == 1:
            return False
        batches.remove(bucket.batch)
        if not batches:
            del self._by_seq[bucket.seq]
        return True


def pad_trailing_batch(inputs: dict, batch_size: int) -> dict:
    """Pad a dict of ``[n, ...]`` host arrays up to ``batch_size`` rows by
    repeating each array's last row (factored from the predictor's
    trailing-partial-batch handling — repeated real rows, never all-pad
    rows, so no fully-masked attention row ever reaches a softmax).

    A no-op (same dict) when the batch is already full.
    """
    n_valid = min(
        int(np.shape(v)[0]) for v in inputs.values()
    ) if inputs else 0
    if n_valid >= batch_size:
        return inputs
    pad = batch_size - n_valid
    return {
        k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
        for k, v in inputs.items()
    }
