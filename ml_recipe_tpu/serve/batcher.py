"""Continuous micro-batching queue with deadline coalescing + backpressure.

The serving engine scatters each request into per-chunk work items; this
batcher coalesces concurrent items that share a bucket seq into the largest
eligible ``(batch, seq)`` bucket:

- a bucket FIRES EARLY the moment its largest batch is full (no reason to
  hold a full program back);
- otherwise it fires when the OLDEST queued item has waited
  ``max_batch_delay_ms`` (the deadline trades a bounded latency floor for
  occupancy — concurrent requests arriving within the window share one
  program launch);
- the queue is BOUNDED: admission past ``queue_size`` raises
  :class:`QueueFullError` immediately (explicit reject-on-full backpressure
  — the HTTP layer turns it into 429 — instead of unbounded growth and
  collapse-under-overload);
- admission is all-or-nothing per request (``submit_many``): a request's
  chunks either all enter the queue or none do, so a rejected request never
  leaves orphan chunks behind.

Draining (SIGTERM): new admissions raise :class:`DrainingError`; everything
already admitted is flushed through normal batch launches (deadlines are
ignored — flush at full speed) and ``drain()`` returns when the queue is
empty and the last in-flight batch has completed.

One worker thread launches batches; the device work itself runs in that
thread (the engine's ``run_fn``), so batches are serialized — matching one
accelerator — while HTTP handler threads only block on their own request's
completion event.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence

from .bucketing import BucketGrid

logger = logging.getLogger(__name__)


class QueueFullError(RuntimeError):
    """Admission rejected: the bounded work queue is full (backpressure)."""


class DrainingError(RuntimeError):
    """Admission rejected: the batcher is draining for shutdown."""


@dataclass
class ChunkWork:
    """One chunk-sized unit of work, opaque to the batcher beyond its
    bucket seq."""

    seq: int
    payload: Any
    enqueued_at: float = field(default=0.0)


class MicroBatcher:
    def __init__(
        self,
        grid: BucketGrid,
        run_fn: Callable[[int, Sequence[ChunkWork]], None],
        *,
        max_batch_delay_ms: float = 10.0,
        queue_size: int = 256,
        fail_fn: Optional[Callable[[Sequence[ChunkWork], BaseException], None]] = None,
        on_depth: Optional[Callable[[int], None]] = None,
        flush_cost_fn: Optional[Callable[[int, int], Optional[float]]] = None,
    ):
        self.grid = grid
        self._run_fn = run_fn
        self._fail_fn = fail_fn
        self._on_depth = on_depth
        # measured per-bucket admission (ROADMAP serving front (d)): when
        # several seqs are deadline-expired at once, ``flush_cost_fn(seq,
        # n_items)`` returns the estimated step cost of the program that
        # would launch (the engine backs it with the autotune cache's
        # persisted ``cost_analysis()`` estimates) and the CHEAPEST flushes
        # first — small fast programs stop queueing behind expensive ones.
        # Returns None (or the hook is None) -> historical order (see
        # ``_rank_flush``). Under sustained cheap-bucket saturation the
        # cheap queue re-expires every loop iteration, so cost ranking
        # alone would starve an expensive bucket indefinitely: once any
        # eligible seq's oldest item has waited ``_starve_after_s``,
        # fairness overrides cost and the oldest flushes next.
        self._flush_cost_fn = flush_cost_fn
        self.max_batch_delay_s = max(0.0, float(max_batch_delay_ms)) / 1e3
        # starvation bound for cost-ranked flushing: several deadlines of
        # grace for the ranking to earn its occupancy, floored so a 0 ms
        # deadline doesn't degenerate to pure oldest-first
        self._starve_after_s = max(8.0 * self.max_batch_delay_s, 0.05)
        self.queue_size = int(queue_size)

        self._pending: Dict[int, deque] = {}
        # long-request scatter groups (ISSUE 20): pre-sliced batches that
        # launch AS-IS, immediately — one request's chunks co-scheduled
        # chunk-parallel instead of interleaving with the coalescing queue.
        # Each entry is one ``(seq, works)`` batch; items count against the
        # same bounded queue.
        self._groups: deque = deque()
        self._n_pending = 0
        self._inflight = False
        self._draining = False
        self._stopped = False
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None

    # -- admission -------------------------------------------------------------

    def submit_many(self, works: Sequence[ChunkWork]) -> None:
        """Admit all of ``works`` or none of them."""
        if not works:
            return
        now = time.monotonic()
        with self._cv:
            if self._draining or self._stopped:
                raise DrainingError("batcher is draining; not accepting work")
            if self._n_pending + len(works) > self.queue_size:
                raise QueueFullError(
                    f"work queue full ({self._n_pending}/{self.queue_size} "
                    f"queued, request needs {len(works)} slots)"
                )
            for w in works:
                w.enqueued_at = now
                self._pending.setdefault(w.seq, deque()).append(w)
            self._n_pending += len(works)
            depth = self._n_pending
            self._cv.notify_all()
        if self._on_depth is not None:
            self._on_depth(depth)

    def submit_group(self, slices: Sequence[Sequence[ChunkWork]]) -> None:
        """Admit a long request's pre-sliced scatter batches, all or
        nothing. Every inner slice launches as ONE dedicated batch, ahead
        of the coalescing queue and with no deadline wait — the request is
        already complete, so holding its chunks back buys nothing. The
        engine slices via ``BucketGrid.scatter_plan``; slices share the
        bounded queue's capacity with ordinary chunk admissions."""
        slices = [list(s) for s in slices if s]
        total = sum(len(s) for s in slices)
        if not total:
            return
        now = time.monotonic()
        with self._cv:
            if self._draining or self._stopped:
                raise DrainingError("batcher is draining; not accepting work")
            if self._n_pending + total > self.queue_size:
                raise QueueFullError(
                    f"work queue full ({self._n_pending}/{self.queue_size} "
                    f"queued, request needs {total} slots)"
                )
            for works in slices:
                for w in works:
                    w.enqueued_at = now
                self._groups.append((works[0].seq, works))
            self._n_pending += total
            depth = self._n_pending
            self._cv.notify_all()
        if self._on_depth is not None:
            self._on_depth(depth)

    @property
    def depth(self) -> int:
        with self._cv:
            return self._n_pending

    def precheck(self, *, check_full: bool = True) -> None:
        """Cheap fast-fail BEFORE the caller pays host-side tokenization:
        raises :class:`DrainingError`/:class:`QueueFullError` when no
        request could possibly be admitted right now (draining, or the
        queue has zero free slots). NOT authoritative — ``submit_many``
        re-checks all-or-nothing under the lock; this only keeps a
        saturated server from burning CPU chunking documents it is about
        to 429 anyway. ``check_full=False`` skips the queue-full arm: with
        the chunk-result cache enabled a request may need ZERO queue slots
        (fully hot), so "queue full" no longer implies "will 429"."""
        with self._cv:
            if self._draining or self._stopped:
                raise DrainingError("batcher is draining; not accepting work")
            if check_full and self._n_pending >= self.queue_size:
                raise QueueFullError(
                    f"work queue full ({self._n_pending}/{self.queue_size} "
                    f"queued)"
                )

    # -- worker ----------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="serve-batcher", daemon=True
        )
        self._thread.start()

    def _full_seq(self) -> Optional[int]:
        """A seq whose pending work already fills its largest bucket."""
        for seq, q in self._pending.items():
            if q and len(q) >= self.grid.max_batch_for(seq):
                return seq
        return None

    def _oldest_seq(self) -> Optional[int]:
        oldest, pick = None, None
        for seq, q in self._pending.items():
            if q and (oldest is None or q[0].enqueued_at < oldest):
                oldest, pick = q[0].enqueued_at, seq
        return pick

    def _eligible_seqs(self) -> list:
        """Seqs allowed to flush right now: all non-empty while draining,
        otherwise those whose oldest item has aged past the deadline."""
        if self._draining:
            return [s for s, q in self._pending.items() if q]
        now = time.monotonic()
        return [
            s for s, q in self._pending.items()
            if q and now - q[0].enqueued_at >= self.max_batch_delay_s
        ]

    def _rank_flush(self, eligible: list) -> int:
        """Which deadline-expired seq flushes first.

        With a ``flush_cost_fn``: cheapest measured step cost first, seqs
        without an estimate after the measured ones in ascending-seq order
        (the documented fallback) — UNLESS some eligible item has already
        waited past the starvation bound, in which case the oldest flushes
        (cost ranking must trade latency ORDER, never bounded service).
        Without the hook, or when NO eligible seq has an estimate (a
        toolchain whose cost_analysis yields nothing must not reorder
        flushes on no evidence): the historical oldest-item-first order.
        """
        oldest = min(eligible, key=lambda s: self._pending[s][0].enqueued_at)
        if self._flush_cost_fn is None:
            return oldest
        waited = time.monotonic() - self._pending[oldest][0].enqueued_at
        if waited >= self._starve_after_s:
            return oldest

        def key(s: int):
            n = min(len(self._pending[s]), self.grid.max_batch_for(s))
            est = self._flush_cost_fn(s, n)
            if est is None:
                return (1, float(s), s)
            return (0, float(est), s)

        keys = {s: key(s) for s in eligible}
        if all(k[0] == 1 for k in keys.values()):
            return oldest
        return min(eligible, key=keys.__getitem__)

    def _take_locked(self) -> Optional[tuple]:
        """Pop the next batch to launch, or None to keep waiting."""
        if self._groups:
            # scatter slices are ready-by-construction batches: launch them
            # before coalescing-queue work so a long request's chunks run
            # back-to-back (its latency is len(plan) device steps, period)
            seq, works = self._groups.popleft()
            self._n_pending -= len(works)
            return seq, works
        seq = self._full_seq()
        if seq is None:
            eligible = self._eligible_seqs()
            if not eligible:
                return None  # deadline not reached, nothing full
            seq = self._rank_flush(eligible)
        q = self._pending[seq]
        take = min(len(q), self.grid.max_batch_for(seq))
        works = [q.popleft() for _ in range(take)]
        self._n_pending -= take
        return seq, works

    def _loop(self) -> None:
        while True:
            with self._cv:
                batch = None
                while batch is None:
                    if self._stopped and self._n_pending == 0:
                        return
                    batch = self._take_locked()
                    if batch is None:
                        # sleep until new work, a deadline, or shutdown
                        timeout = None
                        pick = self._oldest_seq()
                        if pick is not None:
                            deadline = (self._pending[pick][0].enqueued_at
                                        + self.max_batch_delay_s)
                            timeout = max(0.0, deadline - time.monotonic())
                            # a zero-ish timeout busy-spins; floor it
                            timeout = max(timeout, 1e-4)
                        self._cv.wait(timeout)
                seq, works = batch
                self._inflight = True
                depth = self._n_pending
            if self._on_depth is not None:
                self._on_depth(depth)
            try:
                self._run_fn(seq, works)
            except BaseException as exc:  # noqa: BLE001 - fail the batch,
                # keep the loop alive: one poisoned batch must not take the
                # whole serving plane down with it
                logger.exception("batch launch failed (seq=%d, n=%d)",
                                 seq, len(works))
                if self._fail_fn is not None:
                    try:
                        self._fail_fn(works, exc)
                    except Exception:  # noqa: BLE001
                        logger.exception("fail_fn raised")
            finally:
                with self._cv:
                    self._inflight = False
                    self._cv.notify_all()

    # -- shutdown --------------------------------------------------------------

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admissions, flush everything admitted, return True when the
        queue emptied and the last in-flight batch completed (False on
        timeout)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            self._draining = True
            self._cv.notify_all()
            while self._n_pending > 0 or self._inflight:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                self._cv.wait(remaining if remaining is not None else 0.5)
        return True

    def close(self, timeout: float = 10.0) -> None:
        """drain() then stop the worker thread."""
        self.drain(timeout=timeout)
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
