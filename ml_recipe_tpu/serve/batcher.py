"""Continuous micro-batching queue with deadline coalescing + backpressure.

The serving engine scatters each request into per-chunk work items; this
batcher coalesces concurrent items that share a bucket seq into the largest
eligible ``(batch, seq)`` bucket:

- a bucket FIRES EARLY the moment its largest batch is full (no reason to
  hold a full program back);
- otherwise it fires when the OLDEST queued item has waited
  ``max_batch_delay_ms`` (the deadline trades a bounded latency floor for
  occupancy — concurrent requests arriving within the window share one
  program launch);
- the queue is BOUNDED: admission past ``queue_size`` raises
  :class:`QueueFullError` immediately (explicit reject-on-full backpressure
  — the HTTP layer turns it into 429 — instead of unbounded growth and
  collapse-under-overload);
- admission is all-or-nothing per request (``submit_many``): a request's
  chunks either all enter the queue or none do, so a rejected request never
  leaves orphan chunks behind.

Draining (SIGTERM): new admissions raise :class:`DrainingError`; everything
already admitted is flushed through normal batch launches (deadlines are
ignored — flush at full speed) and ``drain()`` returns when the queue is
empty and the last in-flight batch has completed.

One worker thread launches batches; the device work itself runs in that
thread (the engine's ``run_fn``), so batches are serialized — matching one
accelerator — while HTTP handler threads only block on their own request's
completion event.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence

from .bucketing import BucketGrid

logger = logging.getLogger(__name__)


class QueueFullError(RuntimeError):
    """Admission rejected: the bounded work queue is full (backpressure)."""


class DrainingError(RuntimeError):
    """Admission rejected: the batcher is draining for shutdown."""


@dataclass
class ChunkWork:
    """One chunk-sized unit of work, opaque to the batcher beyond its
    bucket seq."""

    seq: int
    payload: Any
    enqueued_at: float = field(default=0.0)


class MicroBatcher:
    def __init__(
        self,
        grid: BucketGrid,
        run_fn: Callable[[int, Sequence[ChunkWork]], None],
        *,
        max_batch_delay_ms: float = 10.0,
        queue_size: int = 256,
        fail_fn: Optional[Callable[[Sequence[ChunkWork], BaseException], None]] = None,
        on_depth: Optional[Callable[[int], None]] = None,
    ):
        self.grid = grid
        self._run_fn = run_fn
        self._fail_fn = fail_fn
        self._on_depth = on_depth
        self.max_batch_delay_s = max(0.0, float(max_batch_delay_ms)) / 1e3
        self.queue_size = int(queue_size)

        self._pending: Dict[int, deque] = {}
        self._n_pending = 0
        self._inflight = False
        self._draining = False
        self._stopped = False
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None

    # -- admission -------------------------------------------------------------

    def submit_many(self, works: Sequence[ChunkWork]) -> None:
        """Admit all of ``works`` or none of them."""
        if not works:
            return
        now = time.monotonic()
        with self._cv:
            if self._draining or self._stopped:
                raise DrainingError("batcher is draining; not accepting work")
            if self._n_pending + len(works) > self.queue_size:
                raise QueueFullError(
                    f"work queue full ({self._n_pending}/{self.queue_size} "
                    f"queued, request needs {len(works)} slots)"
                )
            for w in works:
                w.enqueued_at = now
                self._pending.setdefault(w.seq, deque()).append(w)
            self._n_pending += len(works)
            depth = self._n_pending
            self._cv.notify_all()
        if self._on_depth is not None:
            self._on_depth(depth)

    @property
    def depth(self) -> int:
        with self._cv:
            return self._n_pending

    # -- worker ----------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="serve-batcher", daemon=True
        )
        self._thread.start()

    def _full_seq(self) -> Optional[int]:
        """A seq whose pending work already fills its largest bucket."""
        for seq, q in self._pending.items():
            if q and len(q) >= self.grid.max_batch_for(seq):
                return seq
        return None

    def _oldest_seq(self) -> Optional[int]:
        oldest, pick = None, None
        for seq, q in self._pending.items():
            if q and (oldest is None or q[0].enqueued_at < oldest):
                oldest, pick = q[0].enqueued_at, seq
        return pick

    def _take_locked(self) -> Optional[tuple]:
        """Pop the next batch to launch, or None to keep waiting."""
        seq = self._full_seq()
        if seq is None:
            pick = self._oldest_seq()
            if pick is None:
                return None
            if not self._draining:
                waited = time.monotonic() - self._pending[pick][0].enqueued_at
                if waited < self.max_batch_delay_s:
                    return None  # deadline not reached, nothing full
            seq = pick
        q = self._pending[seq]
        take = min(len(q), self.grid.max_batch_for(seq))
        works = [q.popleft() for _ in range(take)]
        self._n_pending -= take
        return seq, works

    def _loop(self) -> None:
        while True:
            with self._cv:
                batch = None
                while batch is None:
                    if self._stopped and self._n_pending == 0:
                        return
                    batch = self._take_locked()
                    if batch is None:
                        # sleep until new work, a deadline, or shutdown
                        timeout = None
                        pick = self._oldest_seq()
                        if pick is not None:
                            deadline = (self._pending[pick][0].enqueued_at
                                        + self.max_batch_delay_s)
                            timeout = max(0.0, deadline - time.monotonic())
                            # a zero-ish timeout busy-spins; floor it
                            timeout = max(timeout, 1e-4)
                        self._cv.wait(timeout)
                seq, works = batch
                self._inflight = True
                depth = self._n_pending
            if self._on_depth is not None:
                self._on_depth(depth)
            try:
                self._run_fn(seq, works)
            except BaseException as exc:  # noqa: BLE001 - fail the batch,
                # keep the loop alive: one poisoned batch must not take the
                # whole serving plane down with it
                logger.exception("batch launch failed (seq=%d, n=%d)",
                                 seq, len(works))
                if self._fail_fn is not None:
                    try:
                        self._fail_fn(works, exc)
                    except Exception:  # noqa: BLE001
                        logger.exception("fail_fn raised")
            finally:
                with self._cv:
                    self._inflight = False
                    self._cv.notify_all()

    # -- shutdown --------------------------------------------------------------

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admissions, flush everything admitted, return True when the
        queue emptied and the last in-flight batch completed (False on
        timeout)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            self._draining = True
            self._cv.notify_all()
            while self._n_pending > 0 or self._inflight:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                self._cv.wait(remaining if remaining is not None else 0.5)
        return True

    def close(self, timeout: float = 10.0) -> None:
        """drain() then stop the worker thread."""
        self.drain(timeout=timeout)
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
