"""ml_recipe_tpu — TPU-native distributed QA fine-tuning framework.

A ground-up JAX/XLA/pjit/Pallas re-design of the capability surface of
neuro-inc/ml-recipe-distributed-pytorch (multi-host data-parallel BERT/RoBERTa
question-answering fine-tuning on the TF2.0-QA / Natural Questions task):

- SPMD training over a `jax.sharding.Mesh` (data/model/sequence axes) instead of
  process-per-GPU DDP + NCCL.
- A single jitted train step (forward + weighted multi-head loss + grad psum +
  optimizer) with `lax.scan` micro-batching instead of Python-side grad accum.
- Native bf16 mixed precision instead of NVIDIA Apex AMP levels.
- First-party Flax BERT/RoBERTa encoder + 4-head QA model.
- Host-side async input pipeline with fixed-shape batches (XLA-friendly).
- C++ WordPiece/byte-level-BPE tokenizer replacing the Rust `tokenizers` dep.
"""

__version__ = "0.1.0"

# Mesh-invariance contract: threefry bits must be a pure function of
# (key, logical index) on every topology, and the SAME function whether a
# param tree was initialized before or after a Trainer existed — so the
# flag is pinned once, at import, not lazily at first use (a mid-session
# flip would give two trainers in one process different init streams).
# See parallel/compat.ensure_partitionable_threefry.
from .parallel.compat import ensure_partitionable_threefry as _epth

_epth()
del _epth
