"""Checkpoint save/load.

Parity target: reference ``trainer.py:355-403`` — one file holding
``{'model', 'optimizer', 'scheduler', 'global_step'}``, saved by the primary
process only, restored with an optional ``drop_optimizer`` that keeps weights
but discards optimizer/scheduler state (reference ``parser.py:155-156``).

TPU deltas:
- arrays may be sharded over a multi-host mesh; leaves are gathered to full
  host values (``process_allgather``) before the primary writes — the
  reference could simply ``.module.state_dict()`` because every DDP rank held
  a full replica (SURVEY.md §7 hard part (c));
- serialization is flax msgpack instead of ``torch.save`` pickle — no
  arbitrary-code-execution surface, stable across Python versions;
- the JAX PRNG seed/step and the LR schedule are pure functions of
  ``global_step``, so "scheduler state" reduces to the step count (saved for
  format parity).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

from flax import serialization

from ..parallel.sharding import gather_to_host as _to_host

logger = logging.getLogger(__name__)


def save_state_dict(
    path,
    *,
    params,
    opt_state: Any = None,
    loss_scale: Any = None,
    global_step: int = 0,
    extra: Optional[dict] = None,
    is_primary: bool = True,
) -> None:
    """Write one msgpack checkpoint file (reference trainer.py:355-379).

    ``loss_scale`` (the apex-parity scaling state) is stored under its OWN
    key so checkpoints stay structurally loadable when --apex_loss_scale
    changes between save and resume.
    """
    state = {
        "model": serialization.to_state_dict(_to_host(params)),
        "optimizer": (
            serialization.to_state_dict(_to_host(opt_state))
            if opt_state is not None
            else None
        ),
        # LR schedule is a pure function of global_step; kept as a dict for
        # format parity with the reference's scheduler.state_dict().
        "scheduler": {"last_step": global_step},
        "global_step": global_step,
    }
    if loss_scale is not None:
        state["loss_scale"] = serialization.to_state_dict(_to_host(loss_scale))
    if extra:
        state.update(extra)

    if not is_primary:
        return

    path = os.fspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    blob = serialization.msgpack_serialize(state)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(blob)
    os.replace(tmp, path)  # atomic: no torn checkpoints on interrupt
    logger.info(f"State dict was saved to {path}.")


def _strip_legacy_clip_state(node):
    """Recursively rewrite ``{"0": {}, "1": X, ...}`` chain states (whose
    leading element was clip_by_global_norm's EmptyState) to drop the empty
    slot and shift the rest down one key."""
    if isinstance(node, dict):
        if (
            set(node.keys()) >= {"0", "1"}
            and all(k.isdigit() for k in node.keys())
            and node["0"] == {}
        ):
            node = {
                str(int(k) - 1): v for k, v in node.items() if k != "0"
            }
        return {k: _strip_legacy_clip_state(v) for k, v in node.items()}
    return node


def load_state_dict(
    path,
    *,
    params,
    opt_state: Any = None,
    loss_scale: Any = None,
    drop_optimizer: bool = False,
):
    """Restore ``(params, opt_state, loss_scale, global_step)``.

    ``params``/``opt_state``/``loss_scale`` give the target pytree structure
    (flax state-dict restoration is structural). Returns the originals when
    the file does not exist, mirroring the reference's warn-and-continue
    (trainer.py:381-385). A ``loss_scale`` target with no saved state (or
    vice versa) is tolerated: the passed-in value is returned unchanged.
    """
    path = os.fspath(path)
    if not os.path.exists(path):
        logger.warning(f"Checkpoint {path} does not exist, so checkpoint was not loaded.")
        return params, opt_state, loss_scale, None

    with open(path, "rb") as fh:
        state = serialization.msgpack_restore(fh.read())

    new_params = serialization.from_state_dict(params, state["model"])
    logger.info(f"Model weights were loaded from {path} checkpoint.")

    new_opt_state = opt_state
    global_step = int(state.get("global_step", 0))
    if not drop_optimizer and opt_state is not None and state.get("optimizer") is not None:
        try:
            new_opt_state = serialization.from_state_dict(
                opt_state, state["optimizer"]
            )
        except (ValueError, KeyError):
            # Legacy layout: clip_by_global_norm used to live in the optax
            # chain as a leading EmptyState ({"0": {}, "1": core}); clipping
            # moved into the train step, so strip the empty element and retry.
            migrated = _strip_legacy_clip_state(state["optimizer"])
            try:
                new_opt_state = serialization.from_state_dict(opt_state, migrated)
                logger.info("Migrated legacy optimizer state (in-chain clip).")
            except (ValueError, KeyError):
                # Legacy fine-tune layout: a bare optax.masked(tx) state; the
                # chain now appends masked(set_to_zero) for the frozen
                # complement, so the target is a 2-element chain whose second
                # slot holds no values — wrap the legacy state as slot "0"
                # and take slot "1" from the freshly initialized target.
                target_sd = serialization.to_state_dict(opt_state)
                # Only a genuine fine-tune chain qualifies: slot "1" must be
                # the empty masked(set_to_zero) state (no leaves). Any other
                # 2-element chain means a real mismatch — re-raise it rather
                # than silently mis-wrapping the saved state into slot 0.
                def _leafless(node):
                    if isinstance(node, dict):
                        return all(_leafless(v) for v in node.values())
                    return False

                if (
                    isinstance(target_sd, dict)
                    and set(target_sd.keys()) == {"0", "1"}
                    and _leafless(target_sd["1"])
                ):
                    wrapped = {"0": migrated, "1": target_sd["1"]}
                    new_opt_state = serialization.from_state_dict(opt_state, wrapped)
                    logger.info(
                        "Migrated legacy fine-tune optimizer state (masked -> chain)."
                    )
                else:
                    raise
        logger.info(f"Optimizer and scheduler also were restored from {path} checkpoint.")

    new_loss_scale = loss_scale
    if (
        not drop_optimizer
        and loss_scale is not None
        and state.get("loss_scale") is not None
    ):
        new_loss_scale = serialization.from_state_dict(
            loss_scale, state["loss_scale"]
        )

    return new_params, new_opt_state, new_loss_scale, global_step
