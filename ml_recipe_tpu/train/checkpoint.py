"""Checkpoint save/load.

Parity target: reference ``trainer.py:355-403`` — one file holding
``{'model', 'optimizer', 'scheduler', 'global_step'}``, saved by the primary
process only, restored with an optional ``drop_optimizer`` that keeps weights
but discards optimizer/scheduler state (reference ``parser.py:155-156``).

TPU deltas:
- arrays may be sharded over a multi-host mesh; leaves are gathered to full
  host values (``process_allgather``) before the primary writes — the
  reference could simply ``.module.state_dict()`` because every DDP rank held
  a full replica (SURVEY.md §7 hard part (c));
- serialization is flax msgpack instead of ``torch.save`` pickle — no
  arbitrary-code-execution surface, stable across Python versions;
- the JAX PRNG seed/step and the LR schedule are pure functions of
  ``global_step``, so "scheduler state" reduces to the step count (saved for
  format parity).
"""

from __future__ import annotations

import logging
import os
import time
import zlib
from typing import Any, Optional

import numpy as np
from flax import serialization
from flax.traverse_util import empty_node, flatten_dict, unflatten_dict

from ..parallel.sharding import gather_to_host as _to_host
from ..parallel.sharding import needs_collective_gather
from ..resilience.faults import fire as _fault

logger = logging.getLogger(__name__)


class TornCheckpointError(RuntimeError):
    """A sharded checkpoint directory is internally inconsistent (a save was
    interrupted mid-write, or shard files are missing)."""


class CheckpointLayoutError(RuntimeError):
    """The checkpoint's recorded state layout does not match the restore
    target (different optimizer chain, missing/unexpected leaves, or
    incompatible leaf ranks). Raised BEFORE any tensor is restored, with
    the expected-vs-found layouts in the message — the alternative is an
    opaque shape/structure error halfway through the restore."""


def _owned_copy(tree):
    """Deep host copies of every leaf: the async-checkpoint snapshot must
    OWN its buffers — on the CPU runtime ``device_get`` can return views
    into the jax array's buffer, and the very next train step DONATES that
    buffer (the PR-8 heap-corruption class), so a background persist
    reading a view would serialize freed memory."""
    import jax

    return jax.tree_util.tree_map(
        lambda a: np.array(a, copy=True), tree
    )


def snapshot_state(
    *,
    params,
    opt_state: Any = None,
    loss_scale: Any = None,
    global_step: int = 0,
    extra: Optional[dict] = None,
    is_primary: bool = True,
    copy: bool = False,
) -> Optional[dict]:
    """Device -> host snapshot for a single-file save: gathers every leaf
    to full host values and returns the serializable ``state`` dict, or
    None when this process has nothing to persist. This is the only leg of
    a save that must block training; :func:`persist_state` (serialize +
    atomic write) can run on a background thread against the returned
    snapshot. ``copy=True`` deep-copies every gathered leaf so the
    snapshot owns its buffers (required whenever persist is deferred —
    the next train step donates the live arrays).

    ``loss_scale`` (the apex-parity scaling state) is stored under its OWN
    key so checkpoints stay structurally loadable when --apex_loss_scale
    changes between save and resume.
    """
    # Non-writing hosts do the gather ONLY when a leaf genuinely needs a
    # cross-host collective (e.g. ZeRO-sharded opt state without
    # --sharded_checkpoint). Replicated states are assembled from local
    # shards by the primary alone — no all-host materialization.
    if not is_primary and not needs_collective_gather(
        (params, opt_state, loss_scale)
    ):
        return None

    def gather(tree):
        host = _to_host(tree)
        # ownership only matters for a deferred persist, and only the
        # primary persists: non-primary hosts participate in the gather
        # collective but discard the result, so deep-copying it would
        # transiently double their host RAM for bytes never written
        return _owned_copy(host) if copy and is_primary else host

    state = {
        "model": serialization.to_state_dict(gather(params)),
        "optimizer": (
            serialization.to_state_dict(gather(opt_state))
            if opt_state is not None
            else None
        ),
        # LR schedule is a pure function of global_step; kept as a dict for
        # format parity with the reference's scheduler.state_dict().
        "scheduler": {"last_step": global_step},
        "global_step": global_step,
    }
    if loss_scale is not None:
        state["loss_scale"] = serialization.to_state_dict(gather(loss_scale))
    if extra:
        state.update(extra)

    if not is_primary:
        return None
    return state


def persist_state(path, state: dict) -> None:
    """Serialize + atomically write a :func:`snapshot_state` snapshot —
    the CPU/IO tail of a save, safe to run on a background thread (no
    device access, no collectives; the atomic tmp+rename means a crash
    anywhere in here leaves the previous checkpoint at ``path`` intact)."""
    _fault("checkpoint.persist")
    path = os.fspath(path)
    if os.path.isdir(path):
        # a sharded-directory checkpoint previously lived at this name (the
        # flag was toggled off mid-experiment); only replace it when it IS
        # one of ours — anything else is not ours to delete
        if os.path.exists(os.path.join(path, _MANIFEST)):
            import shutil

            shutil.rmtree(path)
        else:
            raise IsADirectoryError(
                f"checkpoint path {path} is a directory that is not a "
                f"sharded checkpoint; refusing to overwrite"
            )
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # atomic: no torn checkpoints on interrupt
    _fault("ckpt.pre_write")
    _atomic_write(path, serialization.msgpack_serialize(state))
    logger.info(f"State dict was saved to {path}.")


def save_state_dict(
    path,
    *,
    params,
    opt_state: Any = None,
    loss_scale: Any = None,
    global_step: int = 0,
    extra: Optional[dict] = None,
    is_primary: bool = True,
) -> None:
    """Write one msgpack checkpoint file (reference trainer.py:355-379):
    snapshot then persist, synchronously on the calling thread."""
    state = snapshot_state(
        params=params, opt_state=opt_state, loss_scale=loss_scale,
        global_step=global_step, extra=extra, is_primary=is_primary,
    )
    if state is None:
        return
    persist_state(path, state)


_MANIFEST = "manifest.msgpack"
_SHARDED_FORMAT = "ml_recipe_tpu.sharded.v1"


def _atomic_write(path: str, blob: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(blob)
    os.replace(tmp, path)


def _recover_interrupted_swap(path: str, staging: str, old: str) -> None:
    """Finish a sharded-save swap that died between its two renames.

    The swap is rename(path -> old) then rename(staging -> path): a crash in
    the window leaves NO live checkpoint at ``path`` while a complete one
    sits in ``staging`` (its manifest is written last, so manifest presence
    means complete) and the previous good one in ``old``. Roll forward to
    the staged checkpoint when it is complete, else roll back to ``old`` —
    never treat either as deletable debris while ``path`` is missing.
    Concurrent callers may race the renames on a shared filesystem: a loser
    sees FileNotFoundError (source already moved) or ENOTEMPTY (target
    already repopulated) — both mean another process recovered first, which
    the re-check of ``path`` confirms.
    """
    if os.path.exists(path):
        return
    try:
        if os.path.isdir(staging) and os.path.exists(
            os.path.join(staging, _MANIFEST)
        ):
            os.rename(staging, path)
            logger.warning(
                f"Recovered interrupted sharded save: completed staged "
                f"checkpoint {staging} promoted to {path}."
            )
        elif os.path.exists(old):
            os.rename(old, path)
            logger.warning(
                f"Recovered interrupted sharded save: previous checkpoint "
                f"{old} restored to {path}."
            )
    except OSError:  # lost a recovery race?
        if not os.path.exists(path):
            raise


def _crc32_of(arr) -> int:
    """crc32 over an array's raw bytes (C-contiguous, so the checksum is a
    pure function of values+shape+dtype, not of the source's strides)."""
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def _norm_bounds(bounds) -> list:
    return [[int(a), int(b)] for a, b in bounds]


def _fold_piece_crcs(pieces) -> int:
    """Combine per-piece crcs into one leaf checksum: fold ``(bounds, crc)``
    records in deterministic (sorted-bounds) order. Detects a swapped or
    bit-rotted piece AND a hand-assembled directory whose pieces disagree
    with what the manifest's writer saved — without ever needing the full
    leaf in one buffer."""
    crc = 0
    for bounds, piece_crc in sorted(
        (tuple(map(tuple, _norm_bounds(b))), int(c)) for b, c in pieces
    ):
        crc = zlib.crc32(repr((bounds, piece_crc)).encode(), crc)
    return crc


def peek_global_step(
    path, *, retries: int = 0, retry_delay: float = 0.05
) -> Optional[int]:
    """``global_step`` of the checkpoint at ``path`` without restoring any
    state, or None when there is no readable checkpoint there. The
    supervisor's progress probe: called between restart attempts, so it
    rolls an interrupted swap forward/back first (same as a load would)
    and treats ANY unreadable/torn checkpoint as absent rather than
    raising — an unreadable checkpoint cannot be resumed from, which is
    exactly what None means.

    ``retries`` re-probes after ``retry_delay`` when the first read comes
    back None: elastic supervisors peek checkpoints a PEER host may be
    mid-swap on, and a transient swap window must not read as 'no
    progress' (the fixed-world supervisor only probes its own files and
    keeps the single-shot default)."""
    step = _peek_global_step_once(path)
    for _ in range(max(0, int(retries))):
        if step is not None:
            break
        time.sleep(retry_delay)
        step = _peek_global_step_once(path)
    return step


def _peek_global_step_once(path) -> Optional[int]:
    path = os.fspath(path)
    if not os.path.exists(path):
        _recover_interrupted_swap(path, path + ".saving", path + ".old")
    if not os.path.exists(path):
        return None
    try:
        if os.path.isdir(path):
            manifest_path = os.path.join(path, _MANIFEST)
            if not os.path.exists(manifest_path):
                return None
            with open(manifest_path, "rb") as fh:
                manifest = serialization.msgpack_restore(fh.read())
            return int(manifest["global_step"])
        with open(path, "rb") as fh:
            state = serialization.msgpack_restore(fh.read())
        return int(state.get("global_step", 0))
    except Exception as e:  # noqa: BLE001 - torn/corrupt == not resumable
        logger.warning(f"Could not peek global_step from {path}: {e!r}")
        return None


def _global_shard_count(arr) -> int:
    """Number of DISTINCT data shards of an array across the whole mesh
    (replicas collapse to one): 1 for replicated/host leaves, N for a
    ZeRO-1 leaf sharded N ways. Computed from sharding METADATA only —
    every process knows the full device->index map without touching remote
    data, which is what lets the manifest record the layout without a
    gather."""
    import jax

    if not isinstance(arr, jax.Array):
        return 1
    try:
        index_map = arr.sharding.devices_indices_map(tuple(arr.shape))
    except Exception:  # noqa: BLE001 - exotic sharding: report unknown as 1
        return 1
    distinct = {
        tuple(
            (int(s.start or 0), int(s.stop if s.stop is not None else dim))
            for s, dim in zip(idx, arr.shape)
        )
        for idx in index_map.values()
    }
    return max(1, len(distinct))


def _flat_state(tree) -> dict:
    """State-dict tree flattened to ``{'a/b/c': leaf}`` (leaves untouched —
    jax.Arrays keep their shardings). Empty subtrees (optax EmptyState
    serializes to ``{}``) are kept as ``empty_node`` leaves so the restored
    structure matches the target exactly."""
    sd = serialization.to_state_dict(tree)
    flat = flatten_dict(sd, keep_empty_nodes=True)
    return {"/".join(map(str, k)): v for k, v in flat.items()}


def peek_checkpoint_layout(path) -> Optional[dict]:
    """Shard layout of the checkpoint at ``path`` WITHOUT loading tensors,
    or None when there is no readable checkpoint there.

    For a sharded directory only the manifest is read: ``shards`` is the
    widest per-leaf sharding recorded at save time (1 = fully replicated
    state, N = a ZeRO-1 save over an N-way data axis), ``opt_sharding``
    the saver's ``--optimizer_sharding`` mode when it recorded one.
    Single-file checkpoints are by construction one replicated shard —
    and msgpack has no lazy skip, so peeking one costs a full deserialize
    (exactly like :func:`peek_global_step` on the same file); the cheap
    no-tensor peek is a property of the sharded-directory format.
    The companion of :func:`peek_global_step` — what the supervisor and
    operators consult before deciding whether a checkpoint can be resumed
    on the current topology (it always can; this tells them what resharding
    the load will perform)."""
    path = os.fspath(path)
    if not os.path.exists(path):
        _recover_interrupted_swap(path, path + ".saving", path + ".old")
    if not os.path.exists(path):
        return None
    try:
        if os.path.isdir(path):
            manifest_path = os.path.join(path, _MANIFEST)
            if not os.path.exists(manifest_path):
                return None
            with open(manifest_path, "rb") as fh:
                manifest = serialization.msgpack_restore(fh.read())
            groups = manifest.get("groups", {})
            return {
                "format": "sharded",
                "global_step": int(manifest.get("global_step", 0)),
                "process_count": int(manifest.get("process_count", 1)),
                "shards": int(manifest.get("shards", 1)),
                "opt_sharding": (manifest.get("extra") or {}).get(
                    "opt_sharding"
                ),
                # the saver's declarative mesh plan ({axis: size}), when it
                # recorded one — the topology this checkpoint was written
                # under; restores reshard onto any live plan regardless
                "mesh_axes": (manifest.get("extra") or {}).get("mesh_axes"),
                # pipeline saves stamp the tick schedule and whether trunk
                # params were stored stage-local; None for non-pipe savers
                "pipe_schedule": (manifest.get("extra") or {}).get(
                    "pipe_schedule"
                ),
                "pipe_param_layout": (manifest.get("extra") or {}).get(
                    "pipe_param_layout"
                ),
                "groups": {g: len(leaves) for g, leaves in groups.items()},
            }
        with open(path, "rb") as fh:
            state = serialization.msgpack_restore(fh.read())
        return {
            "format": "single_file",
            "global_step": int(state.get("global_step", 0)),
            "process_count": 1,
            "shards": 1,
            "opt_sharding": state.get("opt_sharding"),
            "mesh_axes": state.get("mesh_axes"),
            "pipe_schedule": state.get("pipe_schedule"),
            "pipe_param_layout": state.get("pipe_param_layout"),
            "groups": {
                g: len(flatten_dict(state[g], keep_empty_nodes=True))
                for g in ("model", "optimizer", "loss_scale")
                if isinstance(state.get(g), dict)
            },
        }
    except Exception as e:  # noqa: BLE001 - torn/corrupt == not resumable
        logger.warning(f"Could not peek checkpoint layout from {path}: {e!r}")
        return None


def _verify_group_layout(manifest, gname: str, target, path) -> None:
    """Pre-restore layout check of one manifest group against its restore
    target: the leaf KEY SETS must coincide and common leaves must agree on
    rank. Shape differences at equal rank are legal — that is exactly what
    a ZeRO-1 mesh-shape change looks like (padded extents differ with N;
    the Trainer crops/zero-fills onto the live layout) — and are logged,
    not raised. Anything else raises :class:`CheckpointLayoutError` with
    the expected-vs-found layout instead of letting flax die on a shape or
    structure error halfway through the restore."""
    found = manifest["groups"][gname]
    expected = _flat_state(target)
    missing = sorted(k for k in expected if k not in found)
    unexpected = sorted(k for k in found if k not in expected)
    if missing or unexpected:
        raise CheckpointLayoutError(
            f"checkpoint {path} group '{gname}' does not match the restore "
            f"target's layout (saved with shards={manifest.get('shards', 1)}"
            f", opt_sharding="
            f"{(manifest.get('extra') or {}).get('opt_sharding')!r}): "
            f"target expects {len(expected)} leaves, checkpoint holds "
            f"{len(found)}; missing from checkpoint: {missing or 'none'}; "
            f"unexpected in checkpoint: {unexpected or 'none'}"
        )
    resharded = []
    for key, meta in found.items():
        if meta.get("empty") or expected[key] is empty_node:
            continue
        want_shape = tuple(np.shape(expected[key]))
        got_shape = tuple(meta.get("shape", ()))
        if len(want_shape) != len(got_shape):
            raise CheckpointLayoutError(
                f"checkpoint {path} group '{gname}' leaf '{key}' rank "
                f"mismatch: target expects shape {want_shape}, checkpoint "
                f"holds {got_shape} (saved with "
                f"shards={meta.get('shards', 1)}) — a different "
                f"model/optimizer layout, not a mesh-shape change"
            )
        if want_shape != got_shape:
            resharded.append((key, got_shape, want_shape))
    if resharded:
        logger.info(
            "Checkpoint %s group '%s': %d leaves change padded extent "
            "across the restore (ZeRO-1 mesh-shape change, e.g. %s %s -> "
            "%s); the trainer crops/zero-fills onto the live layout.",
            path, gname, len(resharded), resharded[0][0], resharded[0][1],
            resharded[0][2],
        )


def snapshot_state_sharded(
    *,
    params,
    opt_state: Any = None,
    loss_scale: Any = None,
    global_step: int = 0,
    extra: Optional[dict] = None,
    copy: bool = False,
) -> dict:
    """Device -> host snapshot for a sharded save: copies exactly the
    array shards this process owns (``shard.replica_id == 0``) to host and
    builds the manifest — the blocking leg of
    :func:`save_state_dict_sharded`. NOTHING is gathered: peak host memory
    is one local shard set, not the full state. ``copy=True`` deep-copies
    every piece so the snapshot owns its buffers (required whenever
    :func:`persist_state_sharded` is deferred to a background thread — the
    next train step donates the live arrays, and a CPU-runtime shard view
    into a donated buffer would serialize freed memory)."""
    import jax

    def _host_piece(data):
        a = np.asarray(data)
        return np.array(a, copy=True) if copy else a

    groups = {"model": params}
    if opt_state is not None:
        groups["optimizer"] = opt_state
    if loss_scale is not None:
        groups["loss_scale"] = loss_scale

    manifest: dict = {
        "format": _SHARDED_FORMAT,
        "global_step": int(global_step),
        "scheduler": {"last_step": int(global_step)},
        "process_count": int(jax.process_count()),
        "groups": {},
    }
    if extra:
        manifest["extra"] = extra

    owned: dict = {}
    for gname, tree in groups.items():
        flat = _flat_state(tree)
        leaves_meta = {}
        for key, leaf in flat.items():
            arr = leaf
            if arr is empty_node:
                leaves_meta[key] = {"empty": True}
                continue
            # NOTE: do not np.asarray(arr) here — that fetches the FULL
            # array (crashes outright on multi-host non-addressable arrays)
            # and would defeat the no-gather guarantee
            dtype = arr.dtype if hasattr(arr, "dtype") else np.asarray(arr).dtype
            leaves_meta[key] = {
                "shape": list(np.shape(arr)),
                "dtype": str(np.dtype(dtype)),
                # shard layout (ZeRO-1 manifest clause): how many distinct
                # pieces this leaf is stored as across the mesh — readable
                # without loading a single tensor (peek_checkpoint_layout)
                "shards": _global_shard_count(arr),
            }
            group_out = owned.setdefault(gname, {})
            if isinstance(arr, jax.Array):
                for shard in arr.addressable_shards:
                    if shard.replica_id != 0:
                        continue
                    bounds = [
                        [int(s.start or 0), int(s.stop if s.stop is not None else dim)]
                        for s, dim in zip(shard.index, arr.shape)
                    ]
                    data = _host_piece(shard.data)
                    group_out.setdefault(key, []).append(
                        {"bounds": bounds, "data": data, "crc32": _crc32_of(data)}
                    )
            elif jax.process_index() == 0:
                # host (numpy/python) leaf: replicated by construction,
                # the primary owns it
                a = _host_piece(arr)
                group_out.setdefault(key, []).append(
                    {"bounds": [[0, d] for d in a.shape], "data": a,
                     "crc32": _crc32_of(a)}
                )
            # Leaf-level checksum in the manifest whenever THIS process's
            # owned pieces tile the whole leaf (always true single-process;
            # multi-host leaves with remote-owned pieces rely on the
            # per-piece crcs alone — the manifest writer cannot know remote
            # bytes without the gather this save path exists to avoid).
            pieces = group_out.get(key, [])
            covered = sum(
                int(np.prod([b - a for a, b in p["bounds"]], dtype=np.int64))
                if p["bounds"] else 1
                for p in pieces
            )
            want = (
                int(np.prod(leaves_meta[key]["shape"], dtype=np.int64))
                if leaves_meta[key]["shape"] else 1
            )
            if pieces and covered == want:
                leaves_meta[key]["crc32"] = _fold_piece_crcs(
                    [(p["bounds"], p["crc32"]) for p in pieces]
                )
        manifest["groups"][gname] = leaves_meta

    # headline layout field: the widest sharding of the OPTIMIZER state
    # (1 = fully replicated; N = a ZeRO-1 save over an N-way data axis) —
    # what `peek_checkpoint_layout` reports without loading tensors. Scoped
    # to the optimizer group deliberately: a tensor-parallel mesh shards
    # MODEL leaves too, and counting those would misreport a replicated-
    # optimizer TP save as ZeRO-1 (per-leaf `shards` still records every
    # group's true piece counts). Falls back to the model group's count
    # when no optimizer state was saved (params-only checkpoints).
    def _group_shards(gname):
        return [
            int(meta.get("shards", 1))
            for meta in manifest["groups"].get(gname, {}).values()
            if not meta.get("empty")
        ]

    manifest["shards"] = max(
        _group_shards("optimizer") or _group_shards("model") or [1]
    )

    return {
        "manifest": manifest,
        "owned": owned,
        "global_step": int(global_step),
    }


def persist_state_sharded(path, snap: dict) -> None:
    """Write a :func:`snapshot_state_sharded` snapshot to disk: staging
    directory, per-process shard file, manifest-last, atomic swap — the
    IO tail of a sharded save. Cross-process DEVICE-collective barriers
    run here on multi-host worlds, which is why the Trainer only defers
    this leg to the async persist thread on single-process runs (where
    the barriers are no-ops): a background thread enqueueing
    ``sync_global_devices`` concurrently with the main thread's training
    collectives could reorder collective launches across hosts. A crash
    anywhere in here leaves the previous good checkpoint at ``path``
    untouched (manifest presence == completeness)."""
    import jax

    _fault("checkpoint.persist")
    path = os.fspath(path)
    manifest = snap["manifest"]
    global_step = int(snap["global_step"])
    if os.path.isdir(path) and os.listdir(path) and not os.path.exists(
        os.path.join(path, _MANIFEST)
    ):
        # same safety rule as the single-file save: a populated directory
        # that is not one of our checkpoints is not ours to write into
        raise IsADirectoryError(
            f"checkpoint path {path} is a non-empty directory that is not a "
            f"sharded checkpoint; refusing to write into it"
        )

    def _barrier(tag: str) -> None:
        if jax.process_count() > 1:
            from ..parallel import barrier

            barrier(tag)

    # stage everything in a sibling directory; the live path is only touched
    # in the final swap
    staging = path + ".saving"
    old = path + ".old"
    if jax.process_index() == 0:
        import shutil

        _recover_interrupted_swap(path, staging, old)
        for leftover in (staging, old):  # debris from an interrupted save
            if os.path.isdir(leftover):
                shutil.rmtree(leftover)
            elif os.path.isfile(leftover):
                os.remove(leftover)
    _barrier("sharded_ckpt_stage_clear")
    os.makedirs(staging, exist_ok=True)

    # each shard file still carries the step as defense-in-depth torn-save
    # detection (e.g. a checkpoint directory assembled by hand)
    shard_file = os.path.join(staging, f"shard-{jax.process_index():05d}.msgpack")
    _fault("ckpt.pre_shard_write")
    _atomic_write(
        shard_file,
        serialization.msgpack_serialize(
            {"global_step": global_step, "shards": snap["owned"]}
        ),
    )
    # all shard files must land before the manifest exists anywhere
    _barrier("sharded_ckpt_shards_written")
    if jax.process_index() == 0:
        import shutil

        # the chaos suite's canonical kill window: shards durable, manifest
        # (= completeness marker) not yet — the previous checkpoint at
        # `path` must survive untouched
        _fault("ckpt.pre_manifest")
        _atomic_write(
            os.path.join(staging, _MANIFEST),
            serialization.msgpack_serialize(manifest),
        )
        # swap the complete staging dir in; the previous checkpoint (file or
        # directory) is only removed after the new one is fully in place
        if os.path.isfile(path):
            # single-file checkpoint previously at this name (the flag was
            # toggled on mid-experiment)
            os.replace(path, old)
        elif os.path.isdir(path):
            os.rename(path, old)
        _fault("ckpt.mid_swap")
        os.rename(staging, path)
        if os.path.isdir(old):
            shutil.rmtree(old)
        elif os.path.isfile(old):
            os.remove(old)
    # peers may act on the checkpoint (upload, teardown) once the swap landed
    _barrier("sharded_ckpt_swapped")
    logger.info(
        f"Sharded state dict: process {jax.process_index()} wrote its shards "
        f"to {os.path.join(path, os.path.basename(shard_file))}."
    )


def save_state_dict_sharded(
    path,
    *,
    params,
    opt_state: Any = None,
    loss_scale: Any = None,
    global_step: int = 0,
    extra: Optional[dict] = None,
) -> None:
    """Per-host sharded checkpoint (SURVEY §7 hard part (c)).

    ``path`` becomes a DIRECTORY: every process writes exactly the array
    shards it owns (``shard.replica_id == 0`` — each piece of data has one
    canonical owner across the whole mesh, so replicated leaves are written
    once and ZeRO/TP-sharded leaves are written piecewise by their holders);
    the primary also writes a manifest with the tree structure and leaf
    shapes/dtypes. Unlike :func:`save_state_dict`, NOTHING is gathered: peak
    host memory is one local shard, not the full state — this is the path
    that scales to genuinely sharded pod states.

    Layout::

        path/
          manifest.msgpack          # format tag, step, leaf shapes/dtypes
          shard-00000.msgpack       # this process's owned shards
          shard-00001.msgpack       # (one file per process)

    Atomicity: shards are written into a fresh sibling directory
    (``path + '.saving'``); after a cross-process barrier confirms every
    shard file landed, the primary writes the manifest LAST (manifest
    presence therefore implies a complete checkpoint) and swaps the new
    directory in. An interruption at any point leaves the previous good
    checkpoint at ``path`` untouched.

    Implemented as :func:`snapshot_state_sharded` (device -> host, the
    only leg that must block training) followed by
    :func:`persist_state_sharded` (serialize + write + swap) — the split
    ``--async_checkpoint`` runs with the second leg on a background
    thread.
    """
    persist_state_sharded(
        path,
        snapshot_state_sharded(
            params=params, opt_state=opt_state, loss_scale=loss_scale,
            global_step=global_step, extra=extra,
        ),
    )


def load_state_dict_sharded(
    path,
    *,
    params,
    opt_state: Any = None,
    loss_scale: Any = None,
    drop_optimizer: bool = False,
):
    """Restore from a :func:`save_state_dict_sharded` directory.

    Reads every shard file and assembles full host arrays (each process
    needs its own slices only in principle; assembling fully keeps restore
    simple and symmetric with the single-file path — the SAVE side is where
    the gather was the scaling bottleneck). Returns the same 4-tuple as
    :func:`load_state_dict`; the Trainer re-places leaves onto the live
    shardings afterwards.
    """
    path = os.fspath(path)
    with open(os.path.join(path, _MANIFEST), "rb") as fh:
        manifest = serialization.msgpack_restore(fh.read())
    assert manifest.get("format") == _SHARDED_FORMAT, manifest.get("format")

    # read EXACTLY the manifest's process_count shard files — stale
    # higher-index shards from a previous wider-world save are ignored, a
    # missing file is a hard error
    n_proc = int(manifest.get("process_count", 1))
    shard_files = [
        os.path.join(path, f"shard-{p:05d}.msgpack") for p in range(n_proc)
    ]
    for f in shard_files:
        if not os.path.exists(f):
            raise TornCheckpointError(f"sharded checkpoint missing {f}")

    assembled: dict = {g: {} for g in manifest["groups"]}
    filled: dict = {g: {} for g in manifest["groups"]}
    piece_crcs: dict = {g: {} for g in manifest["groups"]}
    for f in shard_files:
        with open(f, "rb") as fh:
            data = serialization.msgpack_restore(fh.read())
        # defense-in-depth torn-save detection: every shard must carry the
        # manifest's step (the staged-dir + swap save makes this unreachable
        # for our own saves; hand-assembled directories can still trip it)
        if int(data["global_step"]) != int(manifest["global_step"]):
            raise TornCheckpointError(
                f"sharded checkpoint is torn: {f} holds step "
                f"{data['global_step']}, manifest holds "
                f"{manifest['global_step']} — a save was interrupted mid-write"
            )
        for gname, leaves in data["shards"].items():
            for key, shards in leaves.items():
                meta = manifest["groups"][gname][key]
                buf = assembled[gname].get(key)
                if buf is None:
                    buf = np.empty(
                        tuple(meta["shape"]), dtype=np.dtype(meta["dtype"])
                    )
                    assembled[gname][key] = buf
                    filled[gname][key] = 0
                for sh in shards:
                    # per-piece bit-rot detection: the checksum travelled
                    # with the bytes, so a flipped bit anywhere in the
                    # stored piece fails loudly here instead of training on
                    if "crc32" in sh and _crc32_of(sh["data"]) != int(sh["crc32"]):
                        raise TornCheckpointError(
                            f"sharded checkpoint corrupt: {gname}/{key} piece "
                            f"{_norm_bounds(sh['bounds'])} in {f} fails its "
                            f"crc32 check (bit rot or a damaged shard file)"
                        )
                    if "crc32" in sh:
                        piece_crcs[gname].setdefault(key, []).append(
                            (sh["bounds"], sh["crc32"])
                        )
                    idx = tuple(slice(a, b) for a, b in sh["bounds"])
                    buf[idx] = sh["data"]
                    filled[gname][key] += int(np.prod(
                        [b - a for a, b in sh["bounds"]], dtype=np.int64
                    )) if sh["bounds"] else 1
    for gname, leaves in manifest["groups"].items():
        for key, meta in leaves.items():
            if meta.get("empty"):
                continue
            want = int(np.prod(meta["shape"], dtype=np.int64)) if meta["shape"] else 1
            got = filled[gname].get(key, 0)
            if got != want:
                raise TornCheckpointError(
                    f"sharded checkpoint incomplete: {gname}/{key} has {got} "
                    f"of {want} elements (missing shard files?)"
                )
            # leaf-level check against the MANIFEST (written by the save
            # that produced the pieces): catches a hand-assembled directory
            # whose shard files are internally consistent but belong to a
            # different save than the manifest — beyond the step check,
            # which such a mix can pass
            if "crc32" in meta:
                folded = _fold_piece_crcs(piece_crcs[gname].get(key, []))
                if folded != int(meta["crc32"]):
                    raise TornCheckpointError(
                        f"sharded checkpoint corrupt: {gname}/{key} piece "
                        f"checksums do not match the manifest (shard files "
                        f"from a different save assembled under this "
                        f"manifest?)"
                    )

    def _restore(target, gname):
        # layout first, tensors second: a mismatched optimizer chain or
        # model fails here with the expected-vs-found layout (and the
        # manifest's shard counts), not with a flax structure/shape error
        # halfway through assembling values
        _verify_group_layout(manifest, gname, target, path)
        flat = dict(assembled[gname])
        for key, meta in manifest["groups"][gname].items():
            if meta.get("empty"):
                flat[key] = empty_node
        sd = unflatten_dict({tuple(k.split("/")): v for k, v in flat.items()})
        return serialization.from_state_dict(target, sd)

    new_params = _restore(params, "model")
    _check_restored_param_shapes(params, new_params, path)
    logger.info(f"Model weights were loaded from sharded checkpoint {path}.")

    new_opt_state = opt_state
    if (
        not drop_optimizer
        and opt_state is not None
        and "optimizer" in manifest["groups"]
    ):
        new_opt_state = _restore(opt_state, "optimizer")
        logger.info("Optimizer state restored from sharded checkpoint.")

    new_loss_scale = loss_scale
    if (
        not drop_optimizer
        and loss_scale is not None
        and "loss_scale" in manifest["groups"]
    ):
        new_loss_scale = _restore(loss_scale, "loss_scale")

    return new_params, new_opt_state, new_loss_scale, int(manifest["global_step"])


def _strip_legacy_clip_state(node):
    """Recursively rewrite ``{"0": {}, "1": X, ...}`` chain states (whose
    leading element was clip_by_global_norm's EmptyState) to drop the empty
    slot and shift the rest down one key."""
    if isinstance(node, dict):
        if (
            set(node.keys()) >= {"0", "1"}
            and all(k.isdigit() for k in node.keys())
            and node["0"] == {}
        ):
            node = {
                str(int(k) - 1): v for k, v in node.items() if k != "0"
            }
        return {k: _strip_legacy_clip_state(v) for k, v in node.items()}
    return node


def _check_restored_param_shapes(target, restored, path) -> None:
    """Hard error when a restored leaf's shape differs from the model's
    (e.g. a preset-table checkpoint restored into a widened long-context
    model — see utils/params.py for why this must be explicit)."""
    from ..utils.params import check_param_shapes

    check_param_shapes(target, restored, f"checkpoint {path}")


def load_state_dict(
    path,
    *,
    params,
    opt_state: Any = None,
    loss_scale: Any = None,
    drop_optimizer: bool = False,
):
    """Restore ``(params, opt_state, loss_scale, global_step)``.

    ``params``/``opt_state``/``loss_scale`` give the target pytree structure
    (flax state-dict restoration is structural). Returns the originals when
    the file does not exist, mirroring the reference's warn-and-continue
    (trainer.py:381-385). A ``loss_scale`` target with no saved state (or
    vice versa) is tolerated: the passed-in value is returned unchanged.
    """
    path = os.fspath(path)
    if not os.path.exists(path):
        # a sharded save interrupted mid-swap may have left the checkpoint
        # in its staging/old sibling — roll it forward/back before giving up
        _recover_interrupted_swap(path, path + ".saving", path + ".old")
    if not os.path.exists(path):
        logger.warning(f"Checkpoint {path} does not exist, so checkpoint was not loaded.")
        return params, opt_state, loss_scale, None

    if os.path.isdir(path):
        # sharded-directory format (save_state_dict_sharded); --last works
        # transparently for either layout
        if not os.path.exists(os.path.join(path, _MANIFEST)):
            # a save interrupted between makedirs and the manifest write
            # leaves a manifest-less directory — same warn-and-continue
            # contract as a missing checkpoint file (reference
            # trainer.py:381-385), with a diagnostic
            logger.warning(
                f"Checkpoint directory {path} has no {_MANIFEST} (interrupted "
                f"first sharded save?); checkpoint was not loaded."
            )
            return params, opt_state, loss_scale, None
        try:
            return load_state_dict_sharded(
                path,
                params=params,
                opt_state=opt_state,
                loss_scale=loss_scale,
                drop_optimizer=drop_optimizer,
            )
        except TornCheckpointError as exc:
            # same warn-and-continue contract the single-file path gets from
            # os.replace atomicity (reference trainer.py:381-385): a damaged
            # --last checkpoint must not crash resume — start fresh / from an
            # epoch checkpoint instead. Direct load_state_dict_sharded
            # callers still see the hard error.
            logger.warning(f"Checkpoint {path} was not loaded: {exc}")
            return params, opt_state, loss_scale, None

    with open(path, "rb") as fh:
        state = serialization.msgpack_restore(fh.read())

    new_params = serialization.from_state_dict(params, state["model"])
    _check_restored_param_shapes(params, new_params, path)
    logger.info(f"Model weights were loaded from {path} checkpoint.")

    new_opt_state = opt_state
    global_step = int(state.get("global_step", 0))
    if not drop_optimizer and opt_state is not None and state.get("optimizer") is not None:
        try:
            new_opt_state = serialization.from_state_dict(
                opt_state, state["optimizer"]
            )
        except (ValueError, KeyError):
            # Legacy layout: clip_by_global_norm used to live in the optax
            # chain as a leading EmptyState ({"0": {}, "1": core}); clipping
            # moved into the train step, so strip the empty element and retry.
            migrated = _strip_legacy_clip_state(state["optimizer"])
            try:
                new_opt_state = serialization.from_state_dict(opt_state, migrated)
                logger.info("Migrated legacy optimizer state (in-chain clip).")
            except (ValueError, KeyError):
                # Legacy fine-tune layout: a bare optax.masked(tx) state; the
                # chain now appends masked(set_to_zero) for the frozen
                # complement, so the target is a 2-element chain whose second
                # slot holds no values — wrap the legacy state as slot "0"
                # and take slot "1" from the freshly initialized target.
                target_sd = serialization.to_state_dict(opt_state)
                # Only a genuine fine-tune chain qualifies: slot "1" must be
                # the empty masked(set_to_zero) state (no leaves). Any other
                # 2-element chain means a real mismatch — re-raise it rather
                # than silently mis-wrapping the saved state into slot 0.
                def _leafless(node):
                    if isinstance(node, dict):
                        return all(_leafless(v) for v in node.values())
                    return False

                if (
                    isinstance(target_sd, dict)
                    and set(target_sd.keys()) == {"0", "1"}
                    and _leafless(target_sd["1"])
                ):
                    wrapped = {"0": migrated, "1": target_sd["1"]}
                    new_opt_state = serialization.from_state_dict(opt_state, wrapped)
                    logger.info(
                        "Migrated legacy fine-tune optimizer state (masked -> chain)."
                    )
                else:
                    raise
        logger.info(f"Optimizer and scheduler also were restored from {path} checkpoint.")

    new_loss_scale = loss_scale
    if (
        not drop_optimizer
        and loss_scale is not None
        and state.get("loss_scale") is not None
    ):
        new_loss_scale = serialization.from_state_dict(
            loss_scale, state["loss_scale"]
        )

    return new_params, new_opt_state, new_loss_scale, global_step
