"""Loss scaling (apex AMP parity).

The reference relies on NVIDIA apex's O1 mixed precision with loss scaling
(trainer.py:128-133,200-202; flag ``apex_loss_scale`` parser.py:150-153). On
TPU the bf16 compute dtype needs no scaling — bf16 shares fp32's exponent
range — so this exists for PARITY and for users who explicitly request it:

- static scale (``--apex_loss_scale 128``): loss is multiplied by S inside
  the jitted step and gradients unscaled by 1/S;
- dynamic scale (``--apex_loss_scale dynamic``): apex-style doubling every
  ``growth_interval`` consecutive finite steps, halving (and SKIPPING the
  optimizer update) on overflow — all inside the compiled step via
  ``lax.cond``-free masking, so no host round-trip.

All state lives in a tiny pytree threaded through the train step.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    scale: jnp.ndarray          # f32 scalar, current multiplier
    growth_count: jnp.ndarray   # i32 scalar, consecutive finite steps
    dynamic: jnp.ndarray        # bool scalar (static scales never adjust)


class OptStateWithLS(NamedTuple):
    """Optimizer state bundled with the scaling state. A dedicated type (not
    a bare 2-tuple): optax chain states are themselves tuples, so a bare
    bundle would be structurally ambiguous to unpackers."""

    inner: object
    ls: LossScaleState


def init_state(scale: float, *, dynamic: bool) -> LossScaleState:
    return LossScaleState(
        scale=jnp.float32(scale),
        growth_count=jnp.int32(0),
        dynamic=jnp.asarray(dynamic),
    )


def scale_loss(loss, state: LossScaleState):
    return loss * state.scale.astype(loss.dtype)


def unscale(grads, state: LossScaleState):
    inv = 1.0 / state.scale
    return jax.tree_util.tree_map(lambda g: g * inv.astype(g.dtype), grads)


def all_finite(grads) -> jnp.ndarray:
    leaves = [jnp.isfinite(g).all() for g in jax.tree_util.tree_leaves(grads)]
    return jnp.stack(leaves).all() if leaves else jnp.asarray(True)


def update_state(
    state: LossScaleState,
    finite: jnp.ndarray,
    *,
    growth_interval: int = 2000,
    growth_factor: float = 2.0,
    backoff_factor: float = 0.5,
    max_scale: float = 2.0 ** 16,
    min_scale: float = 2.0 ** -14,
) -> LossScaleState:
    """Apex-style schedule: halve on overflow (floored at ``min_scale`` so a
    sustained non-finite burst can never underflow the scale to 0, which
    would make ``unscale`` produce inf forever), double after
    ``growth_interval`` consecutive finite steps. No-op for static scales."""
    grew = state.growth_count + 1 >= growth_interval
    new_scale = jnp.where(
        finite,
        jnp.where(
            grew, jnp.minimum(state.scale * growth_factor, max_scale), state.scale
        ),
        jnp.maximum(state.scale * backoff_factor, min_scale),
    )
    new_count = jnp.where(finite & ~grew, state.growth_count + 1, jnp.int32(0))
    return LossScaleState(
        scale=jnp.where(state.dynamic, new_scale, state.scale),
        growth_count=jnp.where(state.dynamic, new_count, jnp.int32(0)),
        dynamic=state.dynamic,
    )


def masked_update(new_tree, old_tree, apply: jnp.ndarray):
    """Elementwise select: the new value on finite steps, the old one on
    overflow steps (the apex 'skip the optimizer step' behaviour, without
    data-dependent control flow inside jit)."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(apply, n, o), new_tree, old_tree
    )
