"""Optimizers and LR schedule (optax).

Parity targets:
- HF ``AdamW(..., correct_bias=False)`` + no-decay param groups for
  bias/LayerNorm (reference init.py:125-138): here one optax chain with a
  decay mask over param paths.
- ``AdaMod`` (reference trainer/optim.py:8-100, vendored from
  lancopku/AdaMod): Adam moments plus an EMA bound on the per-parameter step
  size — re-derived as an optax GradientTransformation.
- ``get_linear_schedule_with_warmup`` (reference trainer.py:116-126): linear
  0→lr over warmup, then linear decay to 0 at num_training_steps.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


def linear_warmup_schedule(lr: float, num_warmup_steps: int, num_training_steps: int):
    """LR(step): step/warmup * lr, then linear decay to 0 (HF semantics)."""

    def schedule(step):
        step = jnp.asarray(step, dtype=jnp.float32)
        warm = jnp.maximum(num_warmup_steps, 1)
        rise = step / warm
        fall = jnp.maximum(
            (num_training_steps - step)
            / jnp.maximum(num_training_steps - num_warmup_steps, 1),
            0.0,
        )
        return lr * jnp.where(step < num_warmup_steps, rise, fall)

    return schedule


class AdaModState(NamedTuple):
    count: jnp.ndarray
    exp_avg: optax.Updates
    exp_avg_sq: optax.Updates
    exp_avg_lr: optax.Updates


def adamod(
    learning_rate,
    b1: float = 0.9,
    b2: float = 0.999,
    beta3: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    decay_mask=None,
    mask=None,
) -> optax.GradientTransformation:
    """AdaMod: Adam with momental bounds on per-param learning rates.

    Matches the reference implementation step-for-step (trainer/optim.py:73-98):
    bias-corrected Adam step size per element, EMA-smoothed (beta3) upper
    bound, decoupled weight decay applied as ``p -= wd * lr * p``.
    ``decay_mask`` (True = decay) reproduces the reference's no-decay param
    groups for bias/LayerNorm (init.py:124-128).
    """

    def init_fn(params):
        zeros = lambda: jax.tree_util.tree_map(jnp.zeros_like, params)
        return AdaModState(
            count=jnp.zeros([], jnp.int32),
            exp_avg=zeros(),
            exp_avg_sq=zeros(),
            exp_avg_lr=zeros(),
        )

    def update_fn(updates, state, params):
        assert params is not None, "adamod requires params for weight decay"
        count = state.count + 1
        # Schedule indexed by the PRE-increment count: the first step trains
        # with schedule(0), matching the HF scheduler and the adam branch.
        lr = learning_rate(state.count) if callable(learning_rate) else learning_rate

        exp_avg = jax.tree_util.tree_map(
            lambda m, g: b1 * m + (1 - b1) * g, state.exp_avg, updates
        )
        exp_avg_sq = jax.tree_util.tree_map(
            lambda v, g: b2 * v + (1 - b2) * g * g, state.exp_avg_sq, updates
        )

        bias1 = 1 - b1 ** count.astype(jnp.float32)
        bias2 = 1 - b2 ** count.astype(jnp.float32)
        step_scale = lr * jnp.sqrt(bias2) / bias1

        def bounded_step(m, v, ema_lr, p, decays):
            denom = jnp.sqrt(v) + eps
            step_size = step_scale / denom
            new_ema_lr = beta3 * ema_lr + (1 - beta3) * step_size
            step_size = jnp.minimum(step_size, new_ema_lr)
            delta = -step_size * m
            if weight_decay != 0 and decays:
                delta = delta - weight_decay * lr * p
            return delta, new_ema_lr

        flat_m, treedef = jax.tree_util.tree_flatten(exp_avg)
        flat_v = treedef.flatten_up_to(exp_avg_sq)
        flat_e = treedef.flatten_up_to(state.exp_avg_lr)
        flat_p = treedef.flatten_up_to(params)
        flat_d = (
            treedef.flatten_up_to(decay_mask)
            if decay_mask is not None
            else [True] * len(flat_m)
        )

        deltas, new_emas = [], []
        for m, v, e, p, d_ in zip(flat_m, flat_v, flat_e, flat_p, flat_d):
            d, ne = bounded_step(m, v, e, p, d_)
            deltas.append(d)
            new_emas.append(ne)

        new_updates = jax.tree_util.tree_unflatten(treedef, deltas)
        new_ema_lr = jax.tree_util.tree_unflatten(treedef, new_emas)

        return new_updates, AdaModState(
            count=count, exp_avg=exp_avg, exp_avg_sq=exp_avg_sq, exp_avg_lr=new_ema_lr
        )

    tx = optax.GradientTransformation(init_fn, update_fn)
    if mask is not None:
        tx = optax.masked(tx, mask)
    return tx


def _scale_by_adam_no_bias_correction(
    b1: float = 0.9, b2: float = 0.999, eps: float = 1e-6
) -> optax.GradientTransformation:
    """Adam moments WITHOUT bias correction — HF ``AdamW(correct_bias=False)``
    as the reference instantiates it (init.py:137)."""

    def init_fn(params):
        zeros = lambda: jax.tree_util.tree_map(jnp.zeros_like, params)
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32), mu=zeros(), nu=zeros()
        )

    def update_fn(updates, state, params=None):
        mu = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g, state.mu, updates)
        nu = jax.tree_util.tree_map(
            lambda v, g: b2 * v + (1 - b2) * g * g, state.nu, updates
        )
        new_updates = jax.tree_util.tree_map(
            lambda m, v: m / (jnp.sqrt(v) + eps), mu, nu
        )
        return new_updates, optax.ScaleByAdamState(count=state.count + 1, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


def _path_names(path) -> list:
    """Key names along one pytree path, as plain strings (DictKey /
    SequenceKey / attr entries normalized alike)."""
    return [str(getattr(p, "key", getattr(p, "idx", p))) for p in path]


def param_path_mask(params, predicate) -> dict:
    """THE shared path walk of every per-parameter boolean mask: one
    boolean leaf per param leaf, ``predicate(names)`` over the leaf's path
    names. ``no_decay_mask`` and ``trainable_mask`` used to each walk the
    tree with their own path-string plumbing, which let the two masks
    disagree on how a new leaf's path reads (and therefore on its
    membership); deriving both from this single walk makes their tree
    structure identical by construction — which is also what lets them
    compose with the ZeRO-1 state plan (parallel/sharding.zero1_plan),
    itself keyed by the same path names."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: bool(predicate(_path_names(path))), params
    )


def no_decay_mask(params) -> dict:
    """True where weight decay applies — everything except biases and
    LayerNorm scales/biases (reference init.py:125-129 no_decay groups)."""

    def decays(names):
        leaf_name = names[-1] if names else ""
        if leaf_name == "bias":
            return False
        if any("layer_norm" in n for n in names):
            return False
        return True

    return param_path_mask(params, decays)


def trainable_mask(params, trainer_params) -> Optional[dict]:
    """Fine-tune module selection (reference init.py:85-123): when
    ``finetune`` is set, only the flagged modules receive updates."""
    if not getattr(trainer_params, "finetune", False):
        return None

    wanted_roots = set()
    if getattr(trainer_params, "finetune_transformer", False):
        wanted_roots.add("transformer")
    if getattr(trainer_params, "finetune_position", False):
        wanted_roots.add("position_outputs")
    if getattr(trainer_params, "finetune_position_reg", False):
        wanted_roots.update(("reg_start", "reg_end"))
    if getattr(trainer_params, "finetune_class", False):
        wanted_roots.add("classifier")

    if not wanted_roots:
        raise AttributeError("Specify at least one module for fine-tuning.")

    return param_path_mask(
        params, lambda names: bool(names) and names[0] in wanted_roots
    )


OPTIMIZER_SHARDING_MODES = ("off", "zero1")


def parse_optimizer_sharding(spec, *, shard_optimizer=None) -> str:
    """Flag domain of ``--optimizer_sharding``: ``off`` (replicate the full
    optimizer state per chip — the historical layout) or ``zero1`` (shard
    every state leaf over the mesh ``data`` axis and run the weight update
    on each replica's shard only). ``None`` defers to the legacy
    ``--shard_optimizer`` boolean so existing configs keep working."""
    if spec is None:
        return "zero1" if shard_optimizer else "off"
    mode = str(spec).strip().lower()
    if mode in ("", "none", "false", "0"):
        return "off"
    if mode in ("true", "1", "on"):
        return "zero1"
    if mode not in OPTIMIZER_SHARDING_MODES:
        raise ValueError(
            f"bad optimizer_sharding {spec!r} (choose from "
            f"{'|'.join(OPTIMIZER_SHARDING_MODES)})"
        )
    return mode


def build_optimizer(
    trainer_params,
    params,
    *,
    num_training_steps: int,
    max_grad_norm: Optional[float] = None,
    warmup_coef: Optional[float] = None,
    optimizer_sharding: Optional[str] = None,
) -> tuple:
    """Optimizer selection + schedule (reference init.py:134-145 +
    trainer.py:116-126 + clip trainer.py:221-225 fused into one chain).

    Returns ``(optax transform, schedule_fn, schedule_count_fn)``.
    ``schedule_count_fn(opt_state)`` reads the schedule step count out of the
    transform's own state, structurally — built here, where the chain layout
    is decided, so no caller ever scans the state tree by leaf name. The
    count only advances on APPLIED updates, which is what makes it the right
    schedule index under loss scaling (overflow steps freeze the whole
    state, count included). ``warmup_coef``, when given, overrides
    ``trainer_params.warmup_coef`` (the Trainer field is the single source
    of truth when built through the Trainer).

    ``optimizer_sharding`` (``off``/``zero1``; ``None`` defers to
    ``trainer_params.optimizer_sharding`` / the legacy ``shard_optimizer``
    boolean) is validated HERE — the chain's transforms are layout-agnostic
    (elementwise over whatever leaves they are given), so the actual state
    placement and the reduce-scatter/all-gather update pattern are applied
    where the state is materialized: ``Trainer.init_opt_state`` and the
    jitted train step. A bad mode must still fail at build time, not at the
    first step.
    """
    parse_optimizer_sharding(
        optimizer_sharding
        if optimizer_sharding is not None
        else getattr(trainer_params, "optimizer_sharding", None),
        shard_optimizer=getattr(trainer_params, "shard_optimizer", False),
    )
    if warmup_coef is None:
        warmup_coef = getattr(trainer_params, "warmup_coef", 0.0)
    lr = trainer_params.lr

    if warmup_coef and warmup_coef > 0:
        num_warmup = int(num_training_steps * warmup_coef)
        schedule = linear_warmup_schedule(lr, num_warmup, num_training_steps)
    else:
        schedule = lambda step: jnp.asarray(lr, jnp.float32)

    decay_mask = no_decay_mask(params)

    if getattr(trainer_params, "optimizer", "adam") == "adam":
        # HF AdamW(correct_bias=False): no bias correction on the moments.
        core = optax.chain(
            _scale_by_adam_no_bias_correction(b1=0.9, b2=0.999, eps=1e-6),
            optax.add_decayed_weights(trainer_params.weight_decay, mask=decay_mask),
            optax.scale_by_learning_rate(schedule),
        )
    else:
        core = adamod(
            schedule,
            weight_decay=trainer_params.weight_decay,
            decay_mask=decay_mask,
        )

    is_adam = getattr(trainer_params, "optimizer", "adam") == "adam"
    has_clip = max_grad_norm is not None and max_grad_norm > 0

    chain = [core]
    if has_clip:
        chain.insert(0, optax.clip_by_global_norm(max_grad_norm))

    tx = optax.chain(*chain)

    tmask = trainable_mask(params, trainer_params)

    def schedule_count_fn(opt_state):
        s = opt_state
        if tmask is not None:
            s = s[0].inner_state  # masked(tx) wrapper, chain slot 0
        s = s[1] if has_clip else s[0]  # `core`'s slot in the outer chain
        if is_adam:
            s = s[0]  # core = chain(adam_moments, decay, lr)
        return s.count  # ScaleByAdamState / AdaModState
    if tmask is not None:
        # optax.masked passes NON-masked updates through UNCHANGED — i.e. the
        # frozen leaves would come out as their raw gradients and be added to
        # the params. Chain a set_to_zero over the frozen complement so
        # frozen modules stay frozen (reference semantics: frozen params are
        # simply never given to the optimizer, init.py:85-123).
        frozen = jax.tree_util.tree_map(lambda m: not m, tmask)
        tx = optax.chain(
            optax.masked(tx, tmask), optax.masked(optax.set_to_zero(), frozen)
        )

    return tx, schedule, schedule_count_fn
