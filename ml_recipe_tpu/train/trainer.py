"""SPMD training runtime.

Parity target: reference ``modules/model/trainer/trainer.py:48-403`` — the
``Trainer`` dataclass: dataloader construction with distributed/weighted
sampling, linear-warmup schedule, mixed precision, gradient accumulation via
``batch_split``, grad clipping, TensorBoard writes, rank-0 test loop with
callbacks, checkpoint save/load with ``drop_optimizer``, debug mode.

TPU-first redesign (SURVEY.md §7):
- One process per host, ONE jitted train step containing
  forward + loss + grad + clip + optimizer update. Data parallelism is not a
  wrapper (DDP, trainer.py:136-142) but a sharding: the batch is laid out
  over the mesh ``data`` axis, params are replicated (or sharded by TP
  rules), and XLA inserts the gradient all-reduce where DDP hooked backward.
  Because the loss is written over the *global* batch, GSPMD's gradient mean
  matches DDP's average semantics exactly (SURVEY.md §7 hard part (e)).
- Gradient accumulation is a ``lax.scan`` over ``batch_split`` micro-batches
  *inside* the compiled step (reference steps the optimizer every Nth
  dataloader batch, trainer.py:284-287) — no host round-trips between
  micro-batches.
- Mixed precision is the model's bf16 compute dtype (native, no loss scaling
  needed on TPU) — replaces the apex AMP plumbing (trainer.py:128-133).
- Eval runs SPMD on all hosts (devices stay busy; reference parks every rank
  but 0 on a barrier, trainer.py:302-319); predictions are gathered to host
  once per step for the metric callbacks, which then agree bit-for-bit on
  every host.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import logging
import os
import time
from collections import defaultdict, deque
from contextlib import nullcontext
from types import SimpleNamespace
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..data.bucketing import BucketedBatch, BucketedDataLoader, synthetic_qa_batch
from ..data.device_prefetch import DevicePrefetcher
from ..data.loader import DataLoader, ShardedBatchSampler
from ..data.packing import (
    DEFAULT_MAX_SEGMENTS,
    DEFAULT_MIN_FRAGMENT,
    PackedBatch,
    PackedDataLoader,
    parse_pack_splitting,
    parse_sequence_packing,
)
from ..losses import PackedWeightedLoss
from ..metrics import AverageMeter
from ..metrics import trace as trace_mod
from ..ops import aot
from ..metrics.trace import XplaneWindow
from ..resilience.faults import fire as _fault
from ..parallel import build_mesh, gather_to_host, make_global_array, shard_params
from ..parallel.plan import ParallelPlan
from ..parallel.sharding import (
    is_single_device,
    leaf_sizes,
    opt_state_bytes_per_chip,
    split_micro,
    zero_pad_tree,
    zero_unpad_tree,
)
from ..utils.hbm import device_hbm_bytes, preflight_bytes
from ..utils.pipeline import LaggedConsumer
from ..utils.profiler import time_profiler
from . import loss_scale as ls_lib
from .callback import TestCallback
from .checkpoint import load_state_dict as _load_ckpt
from .checkpoint import save_state_dict as _save_ckpt
from .optim import build_optimizer, trainable_mask
from .writer import init_writer

logger = logging.getLogger(__name__)

try:  # pragma: no cover - cosmetic only
    from tqdm.auto import tqdm
except Exception:  # noqa: BLE001
    tqdm = None


# --device_prefetch auto: steps timed synchronously at the start of epoch 1
# before the depth decision (the first is discarded when more than one was
# captured — it may carry compile time).
_PREFETCH_AUTO_PROBE_STEPS = 3


def resolve_prefetch_auto(place_s, step_s, *, threshold: float = 0.05) -> int:
    """Depth heuristic for ``--device_prefetch auto``: depth 2 (double
    buffering) when the host-side placement (micro-split + H2D copy) costs
    at least ``threshold`` of the measured step wall — that is the overlap
    double buffering actually buys — else depth 1 (still off the step path,
    no second in-flight batch pinning HBM). Lists may be ragged/empty
    (short epochs): defaults to 1."""
    if not place_s or not step_s:
        return 1
    if len(place_s) > 1:
        place_s, step_s = place_s[1:], step_s[1:]
    place = sum(place_s) / len(place_s)
    step = sum(step_s) / len(step_s)
    return 2 if place >= threshold * max(step, 1e-9) else 1


# The HBM byte arithmetic (device_hbm_bytes / preflight_bytes) lives in
# utils/hbm.py, shared with serve/engine.py's predict-step pre-flight — one
# definition of "projected per-device bytes" for train and predict steps.
# Private aliases keep this module's historical names importable.
_device_hbm_bytes = device_hbm_bytes
_preflight_bytes = preflight_bytes


def reconcile_state_shapes(restored, live):
    """Reshard a restored (host) optimizer-state tree onto the LIVE leaf
    shapes: ``zero1`` stores each sharded leaf zero-padded to its mesh
    data-axis multiple, so a checkpoint taken at mesh N restores at mesh M
    (M != N) — or under ``--optimizer_sharding off``, or vice versa — by
    corner-cropping every leaf to the shape overlap and zero-filling the
    live padding. The pad region is zeros by construction (padded gradients
    are zero there, so Adam moments never leave zero) and never feeds a
    real element's update, which is what makes this crop/fill exact rather
    than approximate."""

    def fix(saved, live_leaf):
        target = tuple(np.shape(live_leaf))
        arr = np.asarray(saved)
        if tuple(arr.shape) == target:
            return saved
        if arr.ndim != len(target):
            raise ValueError(
                f"optimizer-state leaf rank changed across restore: saved "
                f"{arr.shape} vs live {target} — this is a layout mismatch "
                f"(different optimizer chain?), not ZeRO padding"
            )
        arr = arr[tuple(slice(0, min(s, t)) for s, t in zip(arr.shape, target))]
        widths = [(0, t - s) for s, t in zip(arr.shape, target)]
        if any(w for _, w in widths):
            arr = np.pad(arr, widths)
        return arr

    return jax.tree_util.tree_map(fix, restored, live)


def _console_str(meters: dict) -> str:
    return ", ".join(
        f"{k}: {v() if isinstance(v, AverageMeter) else v:.3e}" for k, v in meters.items()
    )


@dataclasses.dataclass
class Trainer:
    model: Any                      # flax Module (QAModel)
    params: Any                     # initial parameter pytree
    loss: Any                       # WeightedLoss
    collate_fun: Any

    trainer_params: Any = None      # namespace driving optimizer/finetune knobs

    train_dataset: Any = None
    test_dataset: Any = None

    writer_dir: Any = None

    mesh: Optional[Mesh] = None

    n_epochs: int = 0

    train_batch_size: int = 32      # GLOBAL optimizer-step batch (documented delta:
                                    # the reference's is per-process, train.py:42-44)
    test_batch_size: int = 32

    batch_split: int = 1
    n_jobs: int = 4

    warmup_coef: float = 0.01
    max_grad_norm: float = 1.0

    train_weights: Any = None       # {'label_weights','sampler_weights'} (init.py:169-201)

    drop_optimizer: bool = False
    debug: bool = False
    seed: int = 0

    # xplane trace of a few steady-state steps (SURVEY.md §5 tracing):
    # directory to dump to, or None to disable. Steps 2-4 of epoch 1 are
    # captured (past compilation, one full accumulation cycle each).
    trace_dir: Any = None

    # PRNG implementation for in-step dropout keys. 'rbg' (XLA
    # RngBitGenerator, hardware-accelerated on TPU) measured 15% faster
    # train steps than 'threefry2x32' on v5e — bert-base seq 512 generates
    # ~300M dropout bits per micro-step and threefry burns VPU cycles on
    # them. Same PRNG-key API; streams differ across impls/backends, which
    # dropout does not care about.
    prng_impl: str = "rbg"

    # ZeRO-1: shard optimizer moments over the mesh data axis (memory 1/N;
    # the reference keeps a full replica per process, SURVEY.md §2.3). XLA
    # all-gathers the sharded param updates — the ZeRO-1 pattern.
    # `optimizer_sharding` is the public mode ('off'|'zero1', the
    # --optimizer_sharding flag); None defers to the legacy
    # `shard_optimizer` boolean so existing callers keep working.
    optimizer_sharding: Any = None
    shard_optimizer: bool = False
    zero_min_size: int = 16384      # leaves smaller than this stay replicated

    # Pipeline schedule + stage-local state (--pipe_schedule, pipe axis >1
    # only): 'gpipe' runs the PR-15 all-m-resident schedule, '1f1b' the
    # one-forward-one-backward tick program that caps resident activations
    # at the in-flight window (parallel/pipeline.py). With
    # `pipe_param_sharding` (default on when the pipe axis is >1 on a
    # multi-device mesh) each rank STORES only its stage's slice of the
    # trunk params and optimizer state (~1/K per-chip bytes); the islands
    # all-gather the slices explicitly per tick.
    pipe_schedule: str = "gpipe"
    pipe_param_sharding: Any = None

    # Bucketed ZeRO-1 collective overlap (--zero1_overlap off|bucketed):
    # 'off' (default) keeps the monolithic flat-vector gradient exchange
    # bit-exactly; 'bucketed' splits the flat f32 accumulation carry into
    # size-targeted contiguous buckets (--zero1_bucket_mb each) so every
    # bucket's reduce-scatter depends only on its own carry — XLA's
    # latency-hiding scheduler can then interleave per-bucket collectives
    # with the remaining update/backward compute instead of fusing one
    # tail exchange behind the full flat vector (the DDP overlap
    # discipline, arxiv 2004.13336). Same arithmetic: bucket vectors
    # concatenate to the monolithic flat vector and the global-norm clip
    # runs over that concatenation — trajectories agree with the
    # unbucketed step to GSPMD reduction-order tolerance (the two
    # programs partition differently), the same bound the
    # zero1-vs-replicated pins hold.
    zero1_overlap: Any = "off"
    zero1_bucket_mb: float = 4.0

    # Async overlapped checkpointing (--async_checkpoint): saves block
    # only for the device->host snapshot; the serialize+write persist
    # runs on a background thread (resilience/checkpoint_async.py) with
    # the same crc32 + atomic-rename discipline as a sync save, a
    # completion barrier before the next save/restore/exit, and the
    # previous valid checkpoint staying newest if a crash lands
    # mid-persist. Off (default) is the historical blocking save.
    async_checkpoint: bool = False

    # Sharded checkpoint writes: each process saves only the array shards it
    # owns (directory layout) instead of gathering the full state to every
    # host for one single-file write — the save path that scales to
    # genuinely sharded pod states (SURVEY §7 hard part (c)). Restores
    # auto-detect either layout.
    sharded_checkpoint: bool = False

    # Optional metrics tap: called as ``on_train_metrics(meters, step=N)``
    # after every consumed train step with the epoch's running AverageMeters
    # (the supported way to capture a loss curve — bench --mode converge and
    # the convergence test use it; the TB writer is unaffected).
    on_train_metrics: Any = None

    # Optional resilience.Watchdog: armed around every train/eval step and
    # checkpoint save so a hung collective / stuck host aborts the process
    # (with stacks dumped) for the supervisor to restart, instead of
    # wedging. None = zero overhead.
    watchdog: Any = None

    # Optional train.telemetry.TrainTelemetry (--metrics_port): per-step
    # wall-time breakdown (data wait / host / device), tokens/sec, padding
    # waste, checkpoint durations, and the slow-step anomaly detector, all
    # exported at /metrics. None (the default) = zero instrumentation, the
    # step loop is untouched. When attached, the step loop blocks on each
    # step's results before dispatching the next (the StepTimer
    # block-until-ready discipline — async dispatch cannot fake device
    # time), trading the one-step metric lag for honest attribution.
    telemetry: Any = None

    # Length-bucketed token-budget batching (data/bucketing.py): a sorted
    # seq grid (e.g. [128, 256, 384, 512]) or None for pad-to-max batching
    # (exactly the historical behavior). Batches are padded to their BUCKET
    # instead of the global max and the per-bucket batch size scales
    # inversely with seq to hold train_batch_size * max(grid) tokens per
    # step; jit compiles one program per occupied bucket (zero probes on a
    # warm autotune cache). Single-process only — multi-host runs fall back
    # with a warning (bucket composition is length-dependent and step
    # shapes would diverge across hosts).
    length_buckets: Any = None

    # Sequence packing (data/packing.py): concatenate short chunks into one
    # fixed (train_batch_size, max_seq_len) row layout with block-diagonal
    # attention — ~every token real, ONE compiled train program. Off (the
    # default) reproduces the bucketed/padded path bit-exactly (pinned in
    # tests/test_dp_equivalence.py). Supersedes length_buckets when both
    # are on (packing subsumes the bucketed win); single-process only, like
    # bucketing — multi-host runs fall back with a warning.
    sequence_packing: Any = False
    # Per-row segment cap: the static S of the [rows, S] label planes and
    # per-segment head outputs.
    pack_max_segments: int = DEFAULT_MAX_SEGMENTS
    # Hole-filling chunk splitting (--pack_splitting off|fill): a chunk
    # that fits no open pack row is split at a label-safe token boundary
    # and its head fragment fills the largest residual hole — the only
    # path below the ~1.6% waste floor quantized chunk mixes impose on any
    # non-splitting packer. 'off' (default) is the pre-splitting packer
    # bit-exactly (pinned in tests/test_dp_equivalence.py). Fragments are
    # ordinary segments; only the gold-span-bearing one carries labels
    # (siblings get ignore-index via segment_mask 0), so examples are
    # never double-counted by the packed loss or row-weighted metrics.
    pack_splitting: Any = "off"
    # No fragment goes below this many tokens (head or tail).
    pack_min_fragment: int = DEFAULT_MIN_FRAGMENT

    # Double-buffered device prefetch (data/device_prefetch.py): keep this
    # many placed global batches in flight on a background thread so the
    # host->device copy of step k+1 overlaps the compute of step k.
    # 0 = synchronous placement (exactly the historical behavior). The
    # trajectory is bit-identical either way (pinned in
    # tests/test_device_prefetch.py). 'auto' times the first few steps of
    # epoch 1 (synchronously) and picks depth 1 vs 2 from the share of the
    # step the host-side placement costs, logging the choice.
    device_prefetch: Any = 0

    # Throttle per-step host overhead: tqdm postfix + TensorBoard writes
    # happen every `log_every` consumed steps (and once more at epoch end)
    # instead of every step. Meters and the on_train_metrics tap still
    # update every step — only the DISPLAY/IO cadence changes.
    log_every: int = 10

    # HBM pre-flight planner: before the first train step executes, lower
    # and compile the jitted step once, read ``compiled.memory_analysis()``,
    # and if the projected HBM requirement exceeds the device limit, raise
    # ``batch_split`` (doubling, honoring the mesh data-axis divisibility)
    # and re-plan — instead of dying in XLA allocation. This is what
    # restores bert-large at its BASELINE-recorded batch-256 settings: the
    # plan runs at split 8 instead of OOMing at split 4, and the decision is
    # logged with before/after byte counts. No-op where the device reports
    # no memory limit (CPU) or the analysis is unavailable.
    hbm_preflight: bool = True

    def __post_init__(self):
        if self.mesh is None:
            self.mesh = build_mesh()

        if self.prng_impl == "threefry2x32":
            # threefry is the mesh-invariant choice (module docstring);
            # that only holds with index-keyed bits — see compat shim.
            from ..parallel.compat import ensure_partitionable_threefry

            ensure_partitionable_threefry()

        # The declarative parallelism plan (parallel/plan.py): every
        # layout below — batch placement, param/opt-state shardings, the
        # ZeRO-1 leaf plan, the pipeline stage layout, the manifest/
        # pre-flight topology records — derives from this ONE object.
        self.plan = ParallelPlan.from_mesh(self.mesh)
        self.pipe_stages = self.plan.pipe_size
        self.pipe_schedule = str(self.pipe_schedule or "gpipe").lower()
        pps = self.pipe_param_sharding
        if isinstance(pps, str):
            pps = {"stage": True, "on": True, "replicated": False,
                   "off": False, "auto": None}.get(pps.lower(), pps)
            if isinstance(pps, str):
                raise ValueError(
                    f"--pipe_param_sharding must be one of "
                    f"auto|stage|replicated, got {self.pipe_param_sharding!r}"
                )
        if pps is None:
            # stage-local storage is the default whenever it can shard:
            # a pipe axis on a one-device mesh has nothing to split
            pps = self.pipe_stages > 1 and not self.plan.single_device
        self.pipe_param_sharding = bool(pps)
        if self.pipe_stages > 1:
            from ..parallel.pipeline import (
                modeled_bubble_fraction, validate_pipeline_plan,
            )

            validate_pipeline_plan(
                self.plan, self.model, batch_split=self.batch_split,
                schedule=self.pipe_schedule,
            )
            logger.info(
                "Pipeline parallelism: %d stages x %d layers over the "
                "pipe axis, %s schedule over %d micro-batch(es) "
                "(modeled bubble %.1f%%, stage-local params %s).",
                self.pipe_stages,
                int(self.model.cfg.num_layers) // self.pipe_stages,
                self.pipe_schedule,
                self.batch_split,
                100.0 * modeled_bubble_fraction(
                    self.pipe_stages, self.batch_split, self.pipe_schedule
                ),
                "on" if self.pipe_param_sharding else "off",
            )
        elif self.pipe_schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"--pipe_schedule must be 'gpipe' or '1f1b', got "
                f"{self.pipe_schedule!r}"
            )

        self.process_index = jax.process_index()
        self.process_count = jax.process_count()
        self.is_primary = self.process_index == 0

        # resolve the optimizer-state layout once; 'zero1' is what the
        # --optimizer_sharding flag threads down, the shard_optimizer bool
        # is the legacy spelling
        from .optim import parse_optimizer_sharding

        self.opt_sharding_mode = parse_optimizer_sharding(
            self.optimizer_sharding, shard_optimizer=self.shard_optimizer
        )

        # collective-overlap mode: validated at construction (a typo must
        # fail here, not silently train monolithic)
        mode = str(self.zero1_overlap or "off").strip().lower()
        if mode not in ("off", "bucketed"):
            raise ValueError(
                f"zero1_overlap must be 'off' or 'bucketed', got "
                f"{self.zero1_overlap!r}"
            )
        self._zero1_overlap_mode = mode
        self.zero1_bucket_count = 0   # set when the bucketed step is built

        # async checkpointing: one single-flight background persist
        # executor for the Trainer's lifetime (its wait() is the
        # completion barrier before the next save / restore / exit)
        self._async_ckpt = None
        if self.async_checkpoint:
            from ..resilience.checkpoint_async import AsyncCheckpointer

            self._async_ckpt = AsyncCheckpointer()

        if self.debug:
            self.n_epochs = 2

        # -- data loaders (trainer.py:100-114,150-181) ------------------------
        self._packing = self._resolve_packing()
        self._seq_grid = None if self._packing else self._resolve_seq_grid()
        if self._packing:
            # packed batches carry per-segment labels + a segment_mask;
            # every head's mean must run over REAL segments only
            self.loss = PackedWeightedLoss(self.loss)
        data_size = int(
            self.mesh.shape.get("data", 1) if hasattr(self.mesh, "shape") else 1
        )
        self.train_dataloader = None
        if self.train_dataset is not None:
            sampler_weights = None
            if self.train_weights is not None:
                sampler_weights = self.train_weights.get("sampler_weights")
            if sampler_weights is not None:
                assert len(sampler_weights) == len(self.train_dataset)
                logger.info("Used train sampler: weighted-with-replacement.")
            else:
                logger.info("Used train sampler: shuffled.")
            self._train_sampler = ShardedBatchSampler(
                len(self.train_dataset),
                self.train_batch_size,
                process_index=self.process_index,
                process_count=self.process_count,
                shuffle=True,
                weights=sampler_weights,
                drop_last=True,
                seed=self.seed,
            )
            if self._packing:
                self.train_dataloader = PackedDataLoader(
                    self.train_dataset, self._train_sampler,
                    self._collate_tokenizer(),
                    max_seq_len=self._collate_max_seq_len(),
                    rows_per_batch=self.train_batch_size,
                    max_segments=self.pack_max_segments,
                    splitting=self.pack_splitting,
                    min_fragment=self.pack_min_fragment,
                    n_jobs=self.n_jobs,
                )
                logger.info(
                    "Sequence packing: %d rows x %d tokens per step, "
                    "max %d segments per row (one compiled program), "
                    "splitting %s.",
                    self.train_batch_size, self.train_dataloader.max_seq_len,
                    self.pack_max_segments, self.train_dataloader.splitting,
                )
            elif self._seq_grid is not None:
                self.train_dataloader = BucketedDataLoader(
                    self.train_dataset, self._train_sampler, self.collate_fun,
                    seq_grid=self._seq_grid,
                    token_budget=self.train_batch_size * self._seq_grid[-1],
                    batch_multiple=self.batch_split * max(data_size, 1),
                    n_jobs=self.n_jobs,
                )
                logger.info(
                    "Length-bucketed batching: grid %s, token budget %d, "
                    "per-bucket batches %s.",
                    self._seq_grid, self.train_dataloader.token_budget,
                    self.train_dataloader.batch_sizes,
                )
            else:
                self.train_dataloader = DataLoader(
                    self.train_dataset, self._train_sampler, self.collate_fun,
                    n_jobs=self.n_jobs,
                )
            logger.info(f"Train dataset len: {len(self.train_dataset)}. #JOBS: {self.n_jobs}.")

        self.test_dataloader = None
        if self.test_dataset is not None:
            self._test_sampler = ShardedBatchSampler(
                len(self.test_dataset),
                self.test_batch_size,
                process_index=self.process_index,
                process_count=self.process_count,
                shuffle=False,
                drop_last=False,
                pad_last=True,
                seed=self.seed,
            )
            if self._packing:
                self.test_dataloader = PackedDataLoader(
                    self.test_dataset, self._test_sampler,
                    self._collate_tokenizer(),
                    max_seq_len=self._collate_max_seq_len(),
                    rows_per_batch=self.test_batch_size,
                    max_segments=self.pack_max_segments,
                    splitting=self.pack_splitting,
                    min_fragment=self.pack_min_fragment,
                    n_jobs=self.n_jobs,
                    pad_last=True,
                )
            elif self._seq_grid is not None:
                self.test_dataloader = BucketedDataLoader(
                    self.test_dataset, self._test_sampler, self.collate_fun,
                    seq_grid=self._seq_grid,
                    token_budget=self.test_batch_size * self._seq_grid[-1],
                    batch_multiple=max(data_size, 1),
                    n_jobs=self.n_jobs,
                    pad_last=True,
                )
            else:
                self.test_dataloader = DataLoader(
                    self.test_dataset, self._test_sampler, self.collate_fun,
                    n_jobs=self.n_jobs,
                )
            logger.info(f"Test dataset len: {len(self.test_dataset)}. #JOBS: {self.n_jobs}.")

        # -- params onto the mesh --------------------------------------------
        # shard_params skips NamedSharding commitment on single-device meshes
        # (GSPMD-partitioned compile path: measured 200x slowdown on the
        # tunneled single-chip backend, and it buys nothing without peers).
        # Under stage-local pipeline storage the trunk leaves land
        # pipe-sharded (parallel/pipeline.stage_param_specs) instead of
        # replicated — ~1/K per-chip param bytes.
        self._stage_param_specs = None
        if self.pipe_param_sharding and self.pipe_stages > 1 \
                and not is_single_device(self.mesh):
            # the plan's derivation (MLA009: stage-spec construction
            # stays inside parallel/)
            self._stage_param_specs = self.plan.stage_specs(self.params)
        self.params = shard_params(
            self.params, self.mesh, pspecs=self._stage_param_specs
        )
        self._param_shardings = (
            None
            if is_single_device(self.mesh)
            else jax.tree_util.tree_map(lambda x: x.sharding, self.params)
        )

        # -- optimizer + schedule (init.py:134-145, trainer.py:116-126) -------
        self.optimizer = None
        self.opt_state = None
        self.scheduler = None
        self._schedule_count = None
        self._planned_steps_per_epoch = None
        self._zero_shardings = None
        self._zero_plan = None
        self._zero_param_shardings = None
        self._opt_state_shardings = None
        self._use_loss_scale = False
        if self.train_dataloader is not None and self.trainer_params is not None:
            micro_batch = self.train_batch_size // self.batch_split
            data_size = int(
                self.mesh.shape.get("data", 1) if hasattr(self.mesh, "shape") else 1
            )
            if micro_batch % max(data_size, 1) != 0:
                raise ValueError(
                    f"Micro-batch {micro_batch} (train_batch_size "
                    f"{self.train_batch_size} / batch_split {self.batch_split}) "
                    f"must divide over the {data_size}-way mesh data axis; "
                    f"lower batch_split or raise train_batch_size."
                )

            # LR-schedule sizing: packed/bucketed epochs take a content-
            # dependent number of steps far below len(dataset)/batch (the
            # packer merges several items per row; bucket batches carry
            # more rows than the global batch). Sizing the schedule from
            # the pad-to-max upper bound silently stretches warmup and
            # never finishes the decay — so derive the estimate from the
            # loader's PLANNED step count (a cheap length-only simulation
            # of epoch 1's packing/bucketing, data/packing.py) instead.
            self._planned_steps_per_epoch = self._plan_schedule_steps()
            steps_per_epoch = (
                self._planned_steps_per_epoch
                if self._planned_steps_per_epoch is not None
                else len(self.train_dataloader)
            )
            num_training_steps = max(self.n_epochs * steps_per_epoch, 1)
            if self.warmup_coef > 0:
                logger.info(
                    f"Warmup schedule is used. #Training steps: {num_training_steps}. "
                    f"#Warmup steps: {int(num_training_steps * self.warmup_coef)}."
                )
            # clipping happens in the train step on the FLAT gradient vector
            # (one fused kernel; optax.clip_by_global_norm costs ~2 launches
            # per parameter tensor) — so the chain is built without it
            self.optimizer, self.scheduler, self._schedule_count = build_optimizer(
                self.trainer_params,
                self.params,
                num_training_steps=num_training_steps,
                max_grad_norm=None,
                warmup_coef=self.warmup_coef,
                optimizer_sharding=self.opt_sharding_mode,
            )
            if getattr(self.trainer_params, "sync_bn", False):
                # Reference converts BatchNorm -> SyncBN (trainer.py:89-95).
                # Under GSPMD there is nothing to convert: normalization
                # statistics computed over the global (data-sharded) batch
                # are cross-replica by construction — XLA inserts the
                # collective; LayerNorm (BERT) is per-token and needs none.
                logger.info(
                    "sync_bn: cross-replica statistics are inherent under "
                    "GSPMD (global-batch reductions); nothing to convert."
                )

            # apex-parity loss scaling (trainer.py:128-133,200-202): 'dynamic'
            # or a static scale; None (the TPU-native default) disables it —
            # bf16 shares fp32's exponent range and needs no scaling.
            raw_scale = getattr(self.trainer_params, "apex_loss_scale", None)
            if raw_scale not in (None, "None"):
                self._use_loss_scale = True
                self._ls_dynamic = raw_scale == "dynamic"
                if not self._ls_dynamic and float(raw_scale) <= 0:
                    raise ValueError(
                        f"apex_loss_scale must be positive or 'dynamic', got "
                        f"{raw_scale!r} (0 would zero every loss and NaN the "
                        f"unscaled grads)."
                    )
                self._ls_init_scale = (
                    2.0 ** 15 if self._ls_dynamic else float(raw_scale)
                )
                logger.info(
                    f"Loss scaling enabled: "
                    f"{'dynamic' if self._ls_dynamic else self._ls_init_scale}."
                )

            self.init_opt_state()

        self.global_step = 0
        self._prefetch_choice = None  # --device_prefetch auto's decision
        self.writer = init_writer(self.is_primary, self.writer_dir)

        self._jit_train_step = None
        self._jit_eval_step = None
        self._preflight_done = not self.hbm_preflight
        self.preflight_report = None
        # AOT program-store dispatch plane (ops/aot.py): placed-shape
        # signature -> compiled executable. Filled by the pre-flight /
        # first-step routing; run_step dispatches through it when the
        # store is enabled, so a warm restart performs ZERO XLA compiles.
        # Cleared whenever the jitted step is rebuilt (batch_split raise).
        self._compiled_steps: dict = {}
        # first train-step store outcome ('hit'/'miss') — the goodput
        # ledger's compile_warmup window carries it as the aot_hit flag
        self._aot_first_outcome = None

    def zero_enabled(self) -> bool:
        """True when the resolved layout is ``zero1`` AND the mesh has a
        multi-way data axis to shard over (a 1-chip 'zero1' run takes the
        replicated path bit-exactly — there is nothing to shard)."""
        return (
            self.opt_sharding_mode == "zero1"
            and not is_single_device(self.mesh)
            and int(self.mesh.shape.get("data", 1)) > 1
        )

    @property
    def effective_opt_sharding(self) -> str:
        """The layout the state ACTUALLY lives in — 'zero1' only when the
        mesh lets it shard; a requested-but-inert zero1 (1-chip mesh)
        reports 'off'. The one spelling every report/manifest/bench field
        uses."""
        return self.opt_sharding_mode if self.zero_enabled() else "off"

    def init_opt_state(self):
        """(Re)initialize ``opt_state`` from ``self.optimizer``, honoring
        ``optimizer_sharding`` (ZeRO-1). Also used by callers that build
        the optimizer themselves (bench, dry-run).

        Under ``zero1`` every state leaf is laid out by the padding-aware
        per-leaf plan (parallel/sharding.zero1_plan): the ``data`` axis
        lands on the largest divisible dim, or the leaf is zero-padded up
        to the next multiple when none divides — so the stored state is
        genuinely 1/N per chip, not "1/N where divisibility allowed".

        Placement is always EXPLICIT on multi-device meshes:
        ``optimizer.init`` reads only param shapes, so XLA prunes the param
        arguments and without ``out_shardings`` every leaf (scalars like
        ``count`` included) would land committed to the default device.
        """
        use_zero = self.zero_enabled()
        stage_pipe = bool(self._stage_param_specs is not None)
        if is_single_device(self.mesh):
            self._zero_shardings = None
            self._zero_plan = None
            self._zero_param_shardings = None
            self._opt_state_shardings = None
            self.opt_state = jax.jit(self.optimizer.init)(self.params)
            self._bundle_ls()
            return

        if use_zero:
            zplan = self.plan.zero1(
                self.params, min_size=self.zero_min_size,
                stage_pipe=stage_pipe,
            )
            self._zero_plan = zplan
            self._zero_param_shardings = self.plan.zero1_param_shardings(
                zplan
            )
            init_fn = lambda p: self.optimizer.init(zero_pad_tree(p, zplan))
        else:
            self._zero_plan = None
            self._zero_param_shardings = None
            init_fn = self.optimizer.init

        state_shapes = jax.eval_shape(init_fn, self.params)
        # the one derivation of the optimizer-state layout (ZeRO-1 over
        # the plan's data axis, stage-local over pipe, or replicated-
        # with-TP-rules) — shared with the layout-consistency tests and
        # checkpoint reconciliation
        shardings = self.plan.opt_state_shardings(
            state_shapes, zero1=use_zero, min_size=self.zero_min_size,
            stage_pipe=stage_pipe,
        )
        self._zero_shardings = shardings if use_zero else None
        self._opt_state_shardings = shardings
        self.opt_state = jax.jit(
            init_fn, out_shardings=shardings
        )(self.params)
        if use_zero:
            logger.info(
                "ZeRO-1: optimizer state sharded over the %d-way data axis "
                "(%.1f MB per chip).",
                int(self.mesh.shape.get("data", 1)),
                opt_state_bytes_per_chip(self.opt_state) / 1e6,
            )
        if stage_pipe:
            logger.info(
                "Stage-local state: trunk params + optimizer moments "
                "sharded over the %d-way pipe axis.", self.pipe_stages,
            )
        self._bundle_ls()

    def _prefetch_auto(self) -> bool:
        return str(self.device_prefetch).strip().lower() == "auto"

    def _prefetch_depth_static(self) -> int:
        """Resolved prefetch depth for loops that do not self-measure (the
        eval loop; train epochs after the auto decision): 0 = synchronous
        placement. 'auto' before any measurement conservatively runs at
        depth 1 (off the step path, no second in-flight batch)."""
        if self._prefetch_auto():
            return self._prefetch_choice if self._prefetch_choice else 1
        return int(self.device_prefetch) if self.device_prefetch else 0

    def _watched(self, label: str, *, scale: float = 1.0):
        """Watchdog frame around a unit of host-side work, yielding a
        per-step ``tick`` (re-entrant: checkpoint barriers arm their own
        frame on top). ``scale`` multiplies the configured timeout for
        units that are legitimately slower than a step. No-op context
        without a watchdog."""
        if self.watchdog is None:
            return nullcontext(lambda *_: None)
        timeout = self.watchdog.timeout * scale if scale != 1.0 else None
        return self.watchdog.watch(label, timeout)

    # -- batch placement ------------------------------------------------------

    def _global_batch(self, tree, *, leading_accum: bool = False):
        """Host numpy -> global jax.Array over the mesh data axis.

        ``leading_accum``: leaves are [G, B, ...] (micro-batch major) and the
        batch dim is axis 1; otherwise leaves are [B, ...] with batch axis 0.

        Ring attention additionally places the token dim over the ``seq``
        axis at ingest, so the embedding lookup and every activation up to
        the ring shard_map are born sequence-sharded — at 8k+ the
        replicated-activation alternative is the memory ceiling.
        """
        return make_global_array(
            tree, self.mesh, batch_axis=1 if leading_accum else 0,
            shard_seq=getattr(self.model, "attention_impl", None) == "ring",
        )

    def _split_micro(self, tree):
        """[B_local, ...] -> [G, B_local/G, ...] for the in-step scan
        (shared implementation: parallel.sharding.split_micro)."""
        return split_micro(tree, self.batch_split)

    def _resolve_packing(self) -> bool:
        """Normalize ``sequence_packing``; with ``length_buckets`` also
        set, packing wins (it subsumes the bucketed padding win) with a log
        line. Multi-host runs are first-class: the loaders derive every
        host's identical pack plan from the shared length oracle
        (data/packing.oracle_read), so step shapes stay in lockstep."""
        # validate the splitting spec up front (fail at construction, not
        # mid-epoch on the loader thread), even when packing is off
        parse_pack_splitting(self.pack_splitting)
        if not parse_sequence_packing(self.sequence_packing):
            return False
        if self.process_count > 1:
            logger.info(
                "sequence_packing: multi-host run — the per-epoch pack "
                "plan derives from the shared length oracle, each host "
                "collates its row slice."
            )
        if self.collate_fun is None or self._collate_tokenizer() is None:
            logger.warning(
                "sequence_packing needs a tokenizer-bound collate_fun "
                "(make_collate_fun); falling back to pad-to-max batching."
            )
            return False
        if self.length_buckets:
            logger.info(
                "sequence_packing supersedes length_buckets: packed rows "
                "are already ~pad-free and compile ONE program (buckets "
                "would only re-introduce per-shape programs)."
            )
        return True

    def _collate_tokenizer(self):
        return getattr(self.collate_fun, "keywords", {}).get("tokenizer")

    def _collate_max_seq_len(self) -> int:
        max_len = getattr(self.collate_fun, "keywords", {}).get("max_seq_len")
        if max_len is None:
            raise ValueError(
                "sequence_packing needs the collate's static max_seq_len "
                "(make_collate_fun(..., max_seq_len=...))"
            )
        return int(max_len)

    def _plan_schedule_steps(self):
        """Planned steps per epoch for LR-schedule sizing, from the
        loader's length-only packing/bucketing simulation (epoch 1's plan
        stands in for all epochs — orderings reshuffle but the length
        population is the same). None = no planner (plain loader: the
        historical ``len(dataloader)`` arithmetic is already exact)."""
        loader = self.train_dataloader
        if not hasattr(loader, "planned_epoch_steps"):
            return None
        try:
            planned = int(loader.planned_epoch_steps(1))
        except Exception as e:  # noqa: BLE001 - planning is best-effort
            logger.warning(
                "LR-schedule step planning failed (%s); falling back to "
                "the len(dataloader) upper bound.", e,
            )
            return None
        planned = max(planned, 1)
        upper = len(loader)
        if planned != upper:
            logger.info(
                "LR schedule sized from the planned epoch step count: %d "
                "steps/epoch (the pad-to-max upper bound would have been "
                "%d — a %.0f%% overshoot that would stretch warmup/decay).",
                planned, upper, 100.0 * (upper - planned) / max(upper, 1),
            )
        return planned

    def _resolve_seq_grid(self):
        """Normalized sorted bucket grid from ``length_buckets`` (or None).
        Extended to cover the collate's static max_seq_len (an item longer
        than every bucket would have nowhere to go). Multi-host runs are
        first-class: every host derives the identical bucket plan from the
        shared length oracle (see BucketedDataLoader)."""
        buckets = self.length_buckets
        if not buckets:
            return None
        if self.process_count > 1:
            logger.info(
                "length_buckets: multi-host run — the per-epoch bucket "
                "plan derives from the shared length oracle, each host "
                "collates its row slice."
            )
        from ..data.bucketing import parse_length_buckets

        # one normalizer for every entry point: sort/dedupe/validate and
        # extend the grid to cover the collate's static max_seq_len
        max_len = getattr(self.collate_fun, "keywords", {}).get("max_seq_len")
        return parse_length_buckets(buckets, max_len)

    @staticmethod
    def _normalize_batch(batch):
        """Loader item -> ``(inputs, labels, meta)``; ``meta`` is the
        BucketedBatch (bucket seq + real_rows) on the bucketed path, the
        PackedBatch (rows + real segment count) on the packed path, None on
        the plain pad-to-max path."""
        if isinstance(batch, (BucketedBatch, PackedBatch)):
            return batch.inputs, batch.labels, batch
        inputs, labels = batch
        return inputs, labels, None


    # -- HBM pre-flight planner ------------------------------------------------

    def _next_batch_split(self) -> Optional[int]:
        """Smallest batch_split above the current one that still divides the
        global batch AND the per-host local batch (``_split_micro`` splits
        the local arrays, so a split legal globally but not locally would
        assert on an 8-host run), and keeps the micro-batch divisible over
        the mesh data axis (the same legality the constructor enforces).
        ``None`` when no such split exists."""
        data_size = int(
            self.mesh.shape.get("data", 1) if hasattr(self.mesh, "shape") else 1
        )
        local_batch = self.train_batch_size // max(self.process_count, 1)
        split = self.batch_split * 2
        while split <= local_batch:
            if (self.train_batch_size % split == 0
                    and local_batch % split == 0
                    and (self.train_batch_size // split) % max(data_size, 1)
                    == 0):
                return split
            split *= 2
        return None

    def _preflight_pipe_fields(self) -> dict:
        """The pipeline-aware slice of both pre-flight reports:
        per-chip PARAM residency (which drops ~1/K under stage-local
        storage — the planner must see the real number, not the
        replicated fiction), the schedule, and the stage -> layer / bytes
        map (so the report can tell you which layers rank 2 owns)."""
        fields = {
            "param_bytes": (
                opt_state_bytes_per_chip(self.params)
                if self.params is not None else None
            ),
            "pipe_schedule": (
                self.pipe_schedule if self.pipe_stages > 1 else None
            ),
            "pipe_param_layout": (
                ("stage" if self._stage_param_specs is not None
                 else "replicated")
                if self.pipe_stages > 1 else None
            ),
            "pipe_stage_layers": None,
            "pipe_stage_param_bytes": None,
        }
        if self.pipe_stages > 1:
            from ..parallel.pipeline import stage_param_bytes

            fields["pipe_stage_layers"] = self.plan.stage_map(
                int(self.model.cfg.num_layers)
            )
            fields["pipe_stage_param_bytes"] = stage_param_bytes(
                self.params, pipe_size=self.pipe_stages,
                model_size=self.plan.model_size,
            )["per_stage_bytes"]
        return fields

    def preflight_train_step(self, host_inputs, host_labels, *,
                             compile_fn=None, limit_bytes=None):
        """HBM pre-flight: lower + compile the jitted train step once at the
        current ``batch_split``, read ``compiled.memory_analysis()``, and if
        the projected per-device requirement exceeds the device HBM, raise
        ``batch_split`` and re-plan — so an over-committed configuration
        (bert-large at batch 256 / split 4) degrades to a running plan with
        a logged decision instead of an XLA allocation failure.

        ``host_inputs``/``host_labels`` are UNSPLIT host batches
        ([B_local, ...] leaves, exactly what the dataloader yields). The
        compiled executable is cached by jit, so the planning compile is
        also the first step's compile — no double work. ``compile_fn`` /
        ``limit_bytes`` exist for tests (mock the XLA memory analysis and
        the device limit); both default to the real thing. Returns the
        decision report dict (also kept as ``self.preflight_report``).
        """
        self._preflight_done = True
        if not self.hbm_preflight:
            return None
        limit = limit_bytes if limit_bytes is not None else _device_hbm_bytes()
        if limit is None:
            logger.info(
                "HBM pre-flight: device reports no memory limit; skipping."
            )
            return None

        report = {
            "limit_bytes": int(limit),
            "batch_split_before": self.batch_split,
            "batch_split": self.batch_split,
            "bytes_before": None,
            "bytes": None,
            "applied": False,
            # plan topology: which axes the step runs under, and how many
            # visible devices the mesh strands (idle but allocated)
            "mesh_axes": self.plan.describe(),
            "mesh_unused_devices": self.plan.unused_devices,
            # optimizer-state residency: under zero1 this is ~1/N of the
            # replicated footprint, which is exactly why the planner must
            # re-measure rather than keep raising batch_split for memory
            # that no longer exists
            "opt_sharding": self.effective_opt_sharding,
            "opt_state_bytes_per_chip": (
                opt_state_bytes_per_chip(self.opt_state)
                if self.opt_state is not None
                else None
            ),
            **self._preflight_pipe_fields(),
        }
        while True:
            if self._jit_train_step is None:
                self._jit_train_step = self._build_train_step()
            if compile_fn is not None:
                compiled = compile_fn(self)
            else:
                inputs = self._global_batch(
                    self._split_micro(host_inputs), leading_accum=True
                )
                labels = self._global_batch(
                    self._split_micro(host_labels), leading_accum=True
                )
                # routed through the AOT program store: a warm restart's
                # planning "compile" is a deserialization (loaded
                # executables expose memory_analysis() too)
                compiled = self._aot_train_step_program(inputs, labels)
            try:
                analysis = compiled.memory_analysis()
            except Exception as e:  # noqa: BLE001 - analysis is best-effort
                logger.info("HBM pre-flight: memory_analysis unavailable "
                            "(%s); skipping.", e)
                break
            need = _preflight_bytes(analysis)
            if need is None:
                logger.info(
                    "HBM pre-flight: memory analysis unavailable; skipping."
                )
                break
            report["bytes"] = int(need)
            if report["bytes_before"] is None:
                report["bytes_before"] = int(need)
            if need <= limit:
                if report["applied"]:
                    logger.warning(
                        "HBM pre-flight: raised batch_split %d -> %d "
                        "(projected %.2f GB -> %.2f GB vs %.2f GB device "
                        "HBM); proceeding with the raised split.",
                        report["batch_split_before"], self.batch_split,
                        report["bytes_before"] / 1e9, need / 1e9,
                        limit / 1e9,
                    )
                break
            new_split = self._next_batch_split()
            if new_split is None:
                logger.warning(
                    "HBM pre-flight: step needs %.2f GB vs %.2f GB device "
                    "HBM and batch_split %d cannot be raised further "
                    "(train_batch_size %d); proceeding — XLA will decide.",
                    need / 1e9, limit / 1e9, self.batch_split,
                    self.train_batch_size,
                )
                break
            logger.warning(
                "HBM pre-flight: step at batch_split %d needs %.2f GB vs "
                "%.2f GB device HBM; raising batch_split to %d.",
                self.batch_split, need / 1e9, limit / 1e9, new_split,
            )
            self.batch_split = new_split
            report["batch_split"] = new_split
            report["applied"] = True
            # the step closed over the old batch_split — rebuild
            self._jit_train_step = None

        self.preflight_report = report
        return report

    def preflight_bucket_steps(self, *, compile_fn=None, limit_bytes=None):
        """Per-bucket HBM pre-flight — the train-side analogue of
        ``QAEngine.preflight_predict_step``: before the first bucketed step
        executes, lower + compile ONE train step per bucket shape (largest
        seq first — it is the heaviest: same token count, O(L^2) attention),
        read each ``memory_analysis()``, and if any bucket exceeds device
        HBM, raise ``batch_split`` and re-derive every bucket's batch size
        (``BucketedDataLoader.rescale``) before re-checking. jit caches by
        shape, so these planning compiles are exactly the compiles the epoch
        would pay anyway — a warm autotune cache makes them zero-probe.

        ``compile_fn(trainer, seq, batch)`` / ``limit_bytes`` exist for
        tests; both default to the real thing. Returns the report dict (also
        ``self.preflight_report``); None when disabled or the device reports
        no memory limit (CPU).
        """
        self._preflight_done = True
        loader = self.train_dataloader
        if not self.hbm_preflight or not isinstance(loader, BucketedDataLoader):
            return None
        limit = limit_bytes if limit_bytes is not None else _device_hbm_bytes()
        if limit is None:
            logger.info(
                "HBM pre-flight: device reports no memory limit; skipping."
            )
            return None
        data_size = int(
            self.mesh.shape.get("data", 1) if hasattr(self.mesh, "shape") else 1
        )
        report = {
            "limit_bytes": int(limit),
            "batch_split_before": self.batch_split,
            "batch_split": self.batch_split,
            "buckets": [],
            "applied": False,
            "mesh_axes": self.plan.describe(),
            "mesh_unused_devices": self.plan.unused_devices,
            "opt_sharding": self.effective_opt_sharding,
            "opt_state_bytes_per_chip": (
                opt_state_bytes_per_chip(self.opt_state)
                if self.opt_state is not None
                else None
            ),
            **self._preflight_pipe_fields(),
        }
        while True:
            if self._jit_train_step is None:
                self._jit_train_step = self._build_train_step()
            over_bytes = None
            checked = []
            stand_down = False
            for seq in sorted(loader.batch_sizes, reverse=True):
                b = loader.batch_sizes[seq]
                if compile_fn is not None:
                    compiled = compile_fn(self, seq, b)
                else:
                    inputs, labels = synthetic_qa_batch(b, seq)
                    # AOT-store routed (see preflight_train_step): per-
                    # bucket planning compiles deserialize on warm restart
                    compiled = self._aot_train_step_program(
                        self._global_batch(
                            self._split_micro(inputs), leading_accum=True
                        ),
                        self._global_batch(
                            self._split_micro(labels), leading_accum=True
                        ),
                    )
                try:
                    analysis = compiled.memory_analysis()
                except Exception as e:  # noqa: BLE001 - analysis is best-effort
                    logger.info("HBM pre-flight: memory_analysis unavailable "
                                "(%s); skipping.", e)
                    stand_down = True
                    break
                need = _preflight_bytes(analysis)
                if need is None:
                    logger.info(
                        "HBM pre-flight: memory analysis unavailable; skipping."
                    )
                    stand_down = True
                    break
                checked.append({"bucket": f"{b}x{seq}", "bytes": int(need)})
                if need > limit:
                    over_bytes = int(need)
                    break
            report["buckets"] = checked
            if stand_down or over_bytes is None:
                break
            new_split = self._next_batch_split()
            if new_split is None:
                logger.warning(
                    "HBM pre-flight: bucket %s needs %.2f GB vs %.2f GB "
                    "device HBM and batch_split %d cannot be raised further; "
                    "proceeding — XLA will decide.",
                    checked[-1]["bucket"], over_bytes / 1e9, limit / 1e9,
                    self.batch_split,
                )
                break
            logger.warning(
                "HBM pre-flight: bucket %s at batch_split %d needs %.2f GB "
                "vs %.2f GB device HBM; raising batch_split to %d and "
                "re-deriving bucket batches.",
                checked[-1]["bucket"], self.batch_split, over_bytes / 1e9,
                limit / 1e9, new_split,
            )
            self.batch_split = new_split
            report["batch_split"] = new_split
            report["applied"] = True
            loader.rescale(new_split * max(data_size, 1))
            # the step closed over the old batch_split — rebuild
            self._jit_train_step = None
        self.preflight_report = report
        return report

    # -- compiled steps --------------------------------------------------------

    def _step_signature(self, dev_inputs, dev_labels) -> str:
        """Stable placed-shape key of one train-step program: every leaf's
        shape+dtype (micro-split accumulation dim included, so a raised
        batch_split keys differently)."""
        parts = []
        for tree in (dev_inputs, dev_labels):
            for leaf in jax.tree_util.tree_leaves(tree):
                parts.append(
                    "x".join(str(d) for d in leaf.shape) + str(leaf.dtype)
                )
        return "_".join(parts)

    def _sharding_signature(self, dev_inputs, dev_labels) -> str:
        """Hash of every argument leaf's placement. AOT executables BAKE
        IN input shardings: on a TP mesh the compiled step's outputs come
        back resharded by its in-step constraints, so the program compiled
        against the initial placement rejects step two's params — where a
        jit wrapper would silently recompile, the dispatch plane must key
        each sharding regime to its own executable (and a warm restart,
        whose restored state already carries the steady-state placement,
        hits the steady-state artifact directly)."""
        specs = {}
        parts = []
        for tree in (self.params, self.opt_state, dev_inputs, dev_labels):
            for leaf in jax.tree_util.tree_leaves(tree):
                sharding = getattr(leaf, "sharding", None)
                text = specs.get(id(sharding))
                if text is None:
                    text = str(getattr(sharding, "spec", sharding))
                    specs[id(sharding)] = text
                parts.append(text)
        digest = hashlib.sha256("|".join(parts).encode()).hexdigest()
        return digest[:12]

    def _model_signature(self) -> str:
        """Model-geometry key component (the serving engine's
        ``_program_cost_key`` discipline: the store is shared per device
        kind — bert-tiny's step must never load as bert-large's)."""
        cfg = getattr(self.model, "cfg", None)
        if cfg is None:
            return "anon"
        sig = (
            f"h{cfg.hidden_size}l{cfg.num_layers}n{cfg.num_heads}"
            f"v{cfg.vocab_size}"
        )
        if self.pipe_stages > 1:
            # gpipe and 1f1b compile DIFFERENT programs over identical
            # shapes + shardings — a schedule flip must never deserialize
            # the other schedule's executable
            layout = "s" if self._stage_param_specs is not None else "r"
            sig += f"-{self.pipe_schedule}{layout}"
        return sig

    def _aot_train_step_program(self, dev_inputs, dev_labels):
        """The train-step executable for these PLACED batches, through the
        AOT program store (ops/aot.py): loaded on a warm restart, compiled
        (and persisted) cold — memoized per placed shape, so the HBM
        pre-flight's program IS the first step's program. With the store
        disabled this is exactly the ``lower().compile()`` HEAD performed
        (and ``run_step`` keeps dispatching through the jit wrapper)."""
        if not hasattr(self._jit_train_step, "lower"):
            # the step fn was swapped for a plain wrapper (debug
            # instrumentation, test recording seams): nothing to lower,
            # dispatch it directly — the pre-store behavior
            return self._jit_train_step
        sig = (
            f"{self._step_signature(dev_inputs, dev_labels)}"
            f"-s{self._sharding_signature(dev_inputs, dev_labels)}"
        )
        program = self._compiled_steps.get(sig)
        if program is not None:
            return program
        store = aot.get()
        program, outcome, seconds = store.load_or_compile_ex(
            "train-step", self._jit_train_step,
            self.params, self.opt_state, dev_inputs, dev_labels,
            self.global_step,
            geometry=sig, plan=aot.plan_signature(self.plan),
            extra=self._model_signature(),
        )
        if outcome != "bypass":
            self._compiled_steps[sig] = program
            if self._aot_first_outcome is None:
                self._aot_first_outcome = outcome
            if self.telemetry is not None:
                self.telemetry.observe_aot(outcome, seconds)
        return program

    def _build_train_step(self):
        # any rebuild (batch_split raise, elastic re-mesh) orphans the
        # dispatch plane's executables — they belong to the old closure
        self._compiled_steps.clear()
        model, loss, optimizer = self.model, self.loss, self.optimizer
        batch_split = self.batch_split
        schedule = self.scheduler
        schedule_count = self._schedule_count
        use_ls = self._use_loss_scale
        # ZeRO-1 closure state: the per-leaf pad/shard plan and the
        # shardings the constrained update runs under (all None when
        # optimizer_sharding is off or the mesh has no multi-way data axis)
        zero_plan = self._zero_plan
        zero_param_shardings = self._zero_param_shardings
        zero_state_shardings = self._zero_shardings
        param_shardings = self._param_shardings
        # stage-local pipeline storage: grads/params/opt-state live
        # pipe-sharded; the update must keep (not silently undo) that layout
        stage_mode = self._stage_param_specs is not None
        opt_state_shardings = self._opt_state_shardings
        # the optimizer chain is built without clip_by_global_norm — the step
        # clips the flat gradient vector itself whenever max_grad_norm is set
        clip_norm = self.max_grad_norm

        # Fine-tune freezing: gradients of non-trainable modules are zeroed
        # before the finite-check / clip / optimizer, so (a) the global clip
        # norm measures trainable gradients only (torch clip_grad_norm_ over
        # the optimized params, reference trainer.py:221-225) and (b) the
        # optax.masked passthrough leaves get a zero update.
        tmask = (
            trainable_mask(self.params, self.trainer_params)
            if self.trainer_params is not None
            else None
        )
        # The flat f32 gradient carry is replicated; on a pure data-parallel
        # mesh grads are replicated anyway so it only fuses launches, but on
        # a model(TP)-axis mesh — or under stage-local pipeline storage,
        # where grads leave the island pipe-sharded — it would all-gather
        # every sharded gradient each micro-batch; use sharding-preserving
        # per-tensor accumulation there instead.
        use_flat = (
            is_single_device(self.mesh)
            or (int(self.mesh.shape.get("model", 1)) <= 1
                and self._stage_param_specs is None)
        )

        # Bucketed ZeRO-1 collective overlap: the single flat carry makes
        # every leaf's reduce-scatter wait on the FULL concatenated
        # gradient (one fused tail exchange after backward); bucket_plan
        # splits the carry into size-targeted contiguous runs whose
        # exchanges are independently schedulable. Only meaningful where
        # the flat carry would be used AND zero1 actually shards (a TP
        # mesh already accumulates per-tensor — maximal independence).
        bucket_plan = None
        if (self._zero1_overlap_mode == "bucketed" and zero_plan is not None
                and int(getattr(self, "pipe_stages", 1) or 1) > 1):
            # the bucketed carry exists to let per-bucket exchanges
            # overlap the sequential accumulation scan; the pipelined
            # body produces the WHOLE gradient in one backward (inside
            # the shard_map island), so there is no carry to interleave
            # — run the monolithic flat exchange, like on TP meshes
            logger.info(
                "zero1_overlap=bucketed under pipeline parallelism: the "
                "pipelined backward yields the full gradient at once "
                "(no accumulation carry to overlap); bucketing is inert."
            )
        elif self._zero1_overlap_mode == "bucketed" and zero_plan is not None:
            if use_flat:
                from ..parallel.sharding import zero1_bucket_plan

                bucket_plan = zero1_bucket_plan(
                    self.params, bucket_mb=self.zero1_bucket_mb
                )
                logger.info(
                    "ZeRO-1 overlap: %d gradient bucket(s) at ~%.1f MB "
                    "target (per-bucket reduce-scatter / all-gather "
                    "independently schedulable).",
                    len(bucket_plan), float(self.zero1_bucket_mb),
                )
            else:
                logger.info(
                    "zero1_overlap=bucketed on a tensor-parallel mesh: "
                    "gradients already accumulate per-tensor (maximal "
                    "per-leaf independence); bucketing is inert."
                )
        elif self._zero1_overlap_mode == "bucketed":
            logger.info(
                "zero1_overlap=bucketed without an active zero1 layout "
                "(--optimizer_sharding off or a 1-chip mesh): nothing to "
                "bucket; the monolithic step runs unchanged."
            )
        self.zero1_bucket_count = len(bucket_plan) if bucket_plan else 0
        if self.telemetry is not None:
            self.telemetry.observe_zero1_buckets(bucket_plan or [])
        # static slice walk of the bucketed carry, plain host ints
        # computed OUTSIDE the traced body: (bucket index, leaf index,
        # offset of the leaf inside its bucket vector)
        bucket_slices = None
        if bucket_plan is not None:
            static_sizes = leaf_sizes(self.params)
            bucket_slices = [
                (bi, k, sum(static_sizes[bk.lo:k]))
                for bi, bk in enumerate(bucket_plan)
                for k in range(bk.lo, bk.hi)
            ]

        pipe = int(getattr(self, "pipe_stages", 1) or 1) > 1
        plan = self.plan
        model_obj = self.model

        def grad_ops(params):
            """Trace-time helpers over the flattened param layout — ONE
            definition of the accumulation layout (flat vector / bucketed
            vectors / per-tensor tree), shared by the sequential and the
            pipelined step bodies."""
            leaves, treedef = jax.tree_util.tree_flatten(params)
            sizes = leaf_sizes(params)
            offsets = np.cumsum([0] + sizes)
            mask_leaves = (
                jax.tree_util.tree_leaves(tmask) if tmask is not None else None
            )

            def flatten_grads(tree):
                return jnp.concatenate(
                    [
                        jnp.ravel(l).astype(jnp.float32)
                        for l in jax.tree_util.tree_leaves(tree)
                    ]
                )

            def unflatten_grads(vec):
                return jax.tree_util.tree_unflatten(
                    treedef,
                    [
                        jax.lax.dynamic_slice_in_dim(vec, int(offsets[i]), sizes[i])
                        .reshape(leaves[i].shape)
                        .astype(leaves[i].dtype)
                        for i in range(len(leaves))
                    ],
                )

            # Bucketed carry: one f32 vector PER BUCKET instead of one
            # global flat vector. Buckets are contiguous leaf runs, so
            # concatenating the bucket vectors reproduces the monolithic
            # flat vector element for element — every consumer runs the
            # same arithmetic while each bucket's reduce-scatter depends
            # only on its own carry. (The two programs still partition
            # differently under GSPMD, so cross-replica reduction
            # placement — and with it the trajectory — agrees to
            # reduction-order tolerance, not bitwise.)
            flatten_grads_bucketed = unflatten_grads_bucketed = None
            if bucket_plan is not None:
                def flatten_grads_bucketed(tree):
                    g_leaves = jax.tree_util.tree_leaves(tree)
                    return tuple(
                        jnp.concatenate(
                            [
                                jnp.ravel(g_leaves[k]).astype(jnp.float32)
                                for k in range(bk.lo, bk.hi)
                            ]
                        )
                        for bk in bucket_plan
                    )

                def unflatten_grads_bucketed(vecs):
                    out = [
                        jax.lax.dynamic_slice_in_dim(vecs[bi], off, sizes[k])
                        .reshape(leaves[k].shape)
                        .astype(leaves[k].dtype)
                        for bi, k, off in bucket_slices
                    ]
                    return jax.tree_util.tree_unflatten(treedef, out)

            def acc_init():
                if bucket_plan is not None:
                    return tuple(
                        jnp.zeros((int(b.size),), jnp.float32)
                        for b in bucket_plan
                    )
                if use_flat:
                    return jnp.zeros((int(offsets[-1]),), jnp.float32)
                return jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), params
                )

            def acc_add(acc, grads):
                if bucket_plan is not None:
                    return tuple(
                        a + f
                        for a, f in zip(acc, flatten_grads_bucketed(grads))
                    )
                if use_flat:
                    return acc + flatten_grads(grads)
                return jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(jnp.float32), acc, grads
                )

            def acc_from_tree(grads):
                """One whole-batch gradient tree -> the accumulation
                layout (the pipelined body produces the summed-over-micros
                gradient in one grad call)."""
                if bucket_plan is not None:
                    return flatten_grads_bucketed(grads)
                if use_flat:
                    return flatten_grads(grads)
                return jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32), grads
                )

            ops = SimpleNamespace(
                leaves=leaves, treedef=treedef, sizes=sizes, offsets=offsets,
                mask_leaves=mask_leaves, flatten_grads=flatten_grads,
                unflatten_grads=unflatten_grads,
                flatten_grads_bucketed=flatten_grads_bucketed,
                unflatten_grads_bucketed=unflatten_grads_bucketed,
                acc_init=acc_init, acc_add=acc_add,
                acc_from_tree=acc_from_tree,
            )
            return ops

        inv = 1.0 / batch_split

        def finish_step(params, opt_state, acc_grads, values, step,
                        ls_state, ops):
            """Everything after gradient accumulation: mean/mask/
            loss-scale/clip on the accumulation layout, the (ZeRO-1)
            optimizer update, lr bookkeeping — identical for both step
            bodies, so the pipelined path cannot drift from the pinned
            sequential arithmetic."""
            # Loss-scale unscale/finite-check and global-norm clipping run
            # over the accumulated f32 gradients. ONE pipeline serves
            # every accumulation layout — `acc_grads` is the flat vector
            # (a single-leaf pytree: every op below is one fused kernel),
            # the bucket-vector tuple, or the per-tensor tree; the math is
            # identical (the single-leaf global norm reduces to the flat
            # formula). Semantics match torch clip_grad_norm_ over the
            # OPTIMIZED params: frozen modules are zeroed first (where/
            # static zeros, not multiply — a frozen module's inf/nan
            # gradient must vanish rather than poison the norm or trip
            # the finite check for params that are not even optimized),
            # and overflow steps contribute zero grads so optimizer
            # moments stay untouched (masked below) and the update is a
            # no-op.
            sizes, leaves, mask_leaves = ops.sizes, ops.leaves, ops.mask_leaves
            grads = jax.tree_util.tree_map(lambda g: g * inv, acc_grads)
            if tmask is not None:
                if bucket_plan is not None:
                    grads = tuple(
                        jnp.where(
                            jnp.concatenate(
                                [
                                    jnp.full((sizes[k],), bool(mask_leaves[k]))
                                    for k in range(bk.lo, bk.hi)
                                ]
                            ),
                            gvec, 0.0,
                        )
                        for bk, gvec in zip(bucket_plan, grads)
                    )
                elif use_flat:
                    mask_vec = jnp.concatenate(
                        [
                            jnp.full((sizes[i],), bool(mask_leaves[i]))
                            for i in range(len(leaves))
                        ]
                    )
                    grads = jnp.where(mask_vec, grads, 0.0)
                else:
                    grads = jax.tree_util.tree_map(
                        lambda g, m: g if m else jnp.zeros_like(g), grads, tmask
                    )
            finite = None
            if use_ls:
                grads = ls_lib.unscale(grads, ls_state)
                finite = ls_lib.all_finite(grads)
                grads = jax.tree_util.tree_map(
                    lambda g: jnp.where(finite, g, 0.0), grads
                )
            if clip_norm is not None and clip_norm > 0:
                # optax.clip_by_global_norm semantics: g * c / max(norm, c).
                # Bucketed: the norm runs over the CONCATENATION of the
                # bucket vectors — the same elements, same reduce shape as
                # the monolithic flat vector, so the clip arithmetic is
                # unchanged; the scalar is the only cross-bucket
                # dependency (inherent to global-norm clipping), and it
                # is one f32.
                if bucket_plan is not None:
                    full = jnp.concatenate(grads)
                    gnorm = jnp.sqrt(jnp.sum(full * full))
                else:
                    gnorm = jnp.sqrt(
                        sum(jnp.sum(g * g) for g in jax.tree_util.tree_leaves(grads))
                    )
                scale = clip_norm / jnp.maximum(gnorm, clip_norm)
                grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
            if bucket_plan is not None:
                grads = ops.unflatten_grads_bucketed(grads)
            elif use_flat:
                grads = ops.unflatten_grads(grads)
            else:
                grads = jax.tree_util.tree_map(
                    lambda g, p: g.astype(p.dtype), grads, params
                )

            if zero_plan is not None:
                # ZeRO-1 update (the --optimizer_sharding zero1 hot path):
                # pad grads and params into the per-leaf plan layout and
                # CONSTRAIN them onto the data axis — GSPMD then lowers the
                # gradient reduction as a reduce-scatter (each replica
                # receives only its shard's sum, never the full gradient)
                # and the weight update touches 1/N of the elements per
                # chip against the 1/N-resident moments; the updates are
                # sliced back to logical shapes and applied to the
                # replicated params, which is the trailing all-gather of
                # the ZeRO-1 pattern (arxiv 2004.13336).
                grads_p = jax.lax.with_sharding_constraint(
                    zero_pad_tree(grads, zero_plan), zero_param_shardings
                )
                params_p = jax.lax.with_sharding_constraint(
                    zero_pad_tree(params, zero_plan), zero_param_shardings
                )
                updates_p, new_opt_state = optimizer.update(
                    grads_p, opt_state, params_p
                )
                # keep the ZeRO layout stable across steps: without the
                # constraint GSPMD may re-layout the donated state to match
                # whatever the update fusion preferred
                new_opt_state = jax.lax.with_sharding_constraint(
                    new_opt_state, zero_state_shardings
                )
                updates = zero_unpad_tree(updates_p, zero_plan, params)
            else:
                updates, new_opt_state = optimizer.update(
                    grads, opt_state, params
                )
                if stage_mode and opt_state_shardings is not None:
                    # keep the stage-local moments pipe-sharded across
                    # steps (same discipline as the ZeRO constraint above)
                    new_opt_state = jax.lax.with_sharding_constraint(
                        new_opt_state, opt_state_shardings
                    )
            new_params = jax.tree_util.tree_map(
                lambda p, u: (p + u).astype(p.dtype), params, updates
            )
            if (zero_plan is not None or stage_mode) \
                    and param_shardings is not None:
                # pin the updated params to the params' own (replicated,
                # TP, or stage-local) layout so the donated buffers keep
                # their shape
                new_params = jax.lax.with_sharding_constraint(
                    new_params, param_shardings
                )

            # lr APPLIED this step: optax scale_by_schedule reads
            # schedule(count) pre-increment. Without loss scaling count ==
            # step; with it, overflow steps are skipped (count freezes), so
            # read the actual count out of the incoming optimizer state.
            if schedule is None:
                values["lr"] = jnp.float32(0)
            elif use_ls and schedule_count is not None:
                values["lr"] = schedule(schedule_count(opt_state))
            else:
                values["lr"] = schedule(step)

            if use_ls:
                # apex semantics: on overflow, skip the whole update (params,
                # moments, schedule count) and back off the scale
                new_params = ls_lib.masked_update(new_params, params, finite)
                new_opt_state = ls_lib.masked_update(new_opt_state, opt_state, finite)
                ls_state = ls_lib.update_state(ls_state, finite)
                values["loss_scale"] = ls_state.scale
                values["grads_finite"] = finite.astype(jnp.float32)
                return new_params, ls_lib.OptStateWithLS(
                    new_opt_state, ls_state
                ), values

            return new_params, new_opt_state, values

        def train_step(params, opt_state, inputs, labels, step):
            ls_state = None
            if use_ls:
                opt_state, ls_state = opt_state.inner, opt_state.ls
            ops = grad_ops(params)
            # Per-step dropout keys: pure function of (seed, step, micro-index).
            base = jax.random.fold_in(
                jax.random.key(self.seed, impl=self.prng_impl), step
            )
            keys = jax.random.split(base, batch_split)

            def loss_fn(p, micro_in, micro_lab, key):
                preds = model.apply(
                    {"params": p}, **micro_in, deterministic=False,
                    rngs={"dropout": key},
                )
                total, values = loss(preds, micro_lab)
                if use_ls:
                    # scale inside the grad; reported `values` stay unscaled
                    return ls_lib.scale_loss(total, ls_state), values
                return total, values

            grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

            # Gradients accumulate in f32. On data-only meshes they live as
            # ONE flat vector: a per-tensor tree_map add in the scan carry
            # costs ~2 kernel launches per parameter tensor per micro-batch
            # (measured 28% of the bert-base step on v5e — launch-bound, the
            # actual traffic is ~7ms); a single fused add + one carry buffer
            # removes it. On TP meshes the per-tensor path keeps each
            # gradient in its parameter's sharding. The layout helpers are
            # shared with the pipelined body (grad_ops above).
            def micro_step(carry, xs):
                g_acc, v_acc = carry
                micro_in, micro_lab, key = xs
                (_, values), grads = grad_fn(params, micro_in, micro_lab, key)
                g_acc = ops.acc_add(g_acc, grads)
                v_acc = jax.tree_util.tree_map(jnp.add, v_acc, values)
                return (g_acc, v_acc), None

            # values structure: probe with a zero-cost eval_shape-compatible init
            v0 = jax.tree_util.tree_map(
                lambda _: jnp.zeros((), jnp.float32),
                loss.value_structure(),
            )

            (acc_grads, values), _ = jax.lax.scan(
                micro_step, (ops.acc_init(), v0), (inputs, labels, keys)
            )
            values = jax.tree_util.tree_map(lambda v: v * inv, values)
            return finish_step(
                params, opt_state, acc_grads, values, step, ls_state, ops
            )

        train_step_pipe = None
        if pipe:
            # Pipeline-parallel body (--mesh pipe:K): the encoder trunk
            # runs the batch_split micro-batches through K contiguous
            # layer stages on the GPipe schedule (parallel/pipeline.py);
            # heads + loss run per micro-batch on the collected outputs,
            # and the gradient of the summed micro losses IS the
            # accumulated gradient the sequential scan produces — so the
            # shared finish_step pins the update arithmetic against the
            # single-axis run.
            from ..parallel.pipeline import (
                apply_qa_heads,
                make_pipeline_encoder,
                make_pipeline_train_step,
            )

            stage_specs = self._stage_param_specs
            pipe_encode = make_pipeline_encoder(
                model_obj, plan, batch_split=batch_split,
                deterministic=False, prng_impl=self.prng_impl,
                stage_specs=stage_specs,
            )
            num_layers = int(model_obj.cfg.num_layers)

            def train_step_pipe(params, opt_state, inputs, labels, step):
                ls_state = None
                if use_ls:
                    opt_state, ls_state = opt_state.inner, opt_state.ls
                ops = grad_ops(params)
                base = jax.random.fold_in(
                    jax.random.key(self.seed, impl=self.prng_impl), step
                )

                def loss_fn(p):
                    seq_out, pooled = pipe_encode(p, inputs, base)
                    v_acc = jax.tree_util.tree_map(
                        lambda _: jnp.zeros((), jnp.float32),
                        loss.value_structure(),
                    )
                    total = jnp.float32(0)
                    for i in range(batch_split):
                        micro_in = jax.tree_util.tree_map(
                            lambda x: x[i], inputs
                        )
                        micro_lab = jax.tree_util.tree_map(
                            lambda x: x[i], labels
                        )
                        am = micro_in.get("attention_mask")
                        if am is None:
                            am = jnp.ones_like(micro_in["input_ids"])
                        preds = apply_qa_heads(
                            model_obj, p, seq_out[i], pooled[i], am,
                            deterministic=False,
                            # head-dropout key: (base, micro, 1+num_layers)
                            # — disjoint from the embed (0) and layer
                            # (1..num_layers) folds the encoder uses
                            dropout_rng=jax.random.fold_in(
                                jax.random.fold_in(base, i), 1 + num_layers
                            ),
                            segment_ids=micro_in.get("segment_ids"),
                            segment_starts=micro_in.get("segment_starts"),
                        )
                        t_i, values_i = loss(preds, micro_lab)
                        total = total + t_i
                        v_acc = jax.tree_util.tree_map(
                            jnp.add, v_acc, values_i
                        )
                    if use_ls:
                        # scaling the summed loss == scaling each micro
                        # loss (linearity), the sequential path's
                        # arithmetic
                        total = ls_lib.scale_loss(total, ls_state)
                    return total, v_acc

                (_, values), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(params)
                values = jax.tree_util.tree_map(lambda v: v * inv, values)
                acc_grads = ops.acc_from_tree(grads)
                return finish_step(
                    params, opt_state, acc_grads, values, step, ls_state,
                    ops,
                )

            if self.pipe_schedule == "1f1b":
                # 1F1B body: forward, heads, loss AND backward run inside
                # one manual-VJP island (parallel/pipeline.py) whose grads
                # are proven equal to the sequential scan's — so the same
                # finish_step pins the update arithmetic. Activation
                # residency is capped at the in-flight window instead of
                # all batch_split micro-batches.
                pipe_run = make_pipeline_train_step(
                    model_obj, loss, plan, batch_split=batch_split,
                    prng_impl=self.prng_impl, stage_specs=stage_specs,
                )

                def train_step_pipe(params, opt_state, inputs, labels,
                                    step):
                    ls_state = None
                    if use_ls:
                        opt_state, ls_state = opt_state.inner, opt_state.ls
                    ops = grad_ops(params)
                    base = jax.random.fold_in(
                        jax.random.key(self.seed, impl=self.prng_impl),
                        step,
                    )
                    scale = (
                        ls_state.scale if use_ls else jnp.float32(1.0)
                    )
                    grads, values = pipe_run(
                        params, inputs, labels, base, scale
                    )
                    values = jax.tree_util.tree_map(
                        lambda v: v * inv, values
                    )
                    acc_grads = ops.acc_from_tree(grads)
                    return finish_step(
                        params, opt_state, acc_grads, values, step,
                        ls_state, ops,
                    )

        return jax.jit(
            train_step_pipe if pipe else train_step, donate_argnums=(0, 1)
        )

    def _build_eval_step(self):
        model, loss = self.model, self.loss

        def eval_step(params, inputs, labels):
            preds = model.apply({"params": params}, **inputs, deterministic=True)
            _, values = loss(preds, labels)
            return preds, values

        return jax.jit(eval_step)

    # -- console / writer (trainer.py:206-219) --------------------------------

    def _update_writer(self, meters: dict, *, prefix: str, step: Optional[int] = None):
        if self.writer is None:
            return
        for k, v in meters.items():
            self.writer.add_scalar(
                f"{prefix}/{k}",
                v() if isinstance(v, AverageMeter) else v,
                global_step=self.global_step if step is None else step,
            )

    # -- train loop (trainer.py:253-300) --------------------------------------

    def train(self, after_epoch_funcs=None):
        if self.train_dataloader is None:
            logger.warning("No train dataset was provided; train() is a no-op.")
            return

        after_epoch_funcs = after_epoch_funcs or []

        with self.mesh:
            for epoch_i in range(1, self.n_epochs + 1):
                self._train(epoch_i)
                for func in after_epoch_funcs:
                    func(epoch_i)

    @time_profiler
    def _train(self, epoch_i):
        if self._jit_train_step is None:
            self._jit_train_step = self._build_train_step()

        self.train_dataloader.set_epoch(epoch_i)
        avg_meters: dict = defaultdict(AverageMeter)
        bucketed = isinstance(self.train_dataloader, BucketedDataLoader)
        packed = isinstance(self.train_dataloader, PackedDataLoader)
        # variable per-step example counts: weight each step's mean by them
        weighted = bucketed or packed

        if bucketed and not self._preflight_done:
            # per-bucket plan BEFORE any batch is drawn: may raise
            # batch_split and re-derive the loader's bucket batch sizes
            self.preflight_bucket_steps()

        iterator = self.train_dataloader
        tqdm_data = None
        if tqdm is not None:
            tqdm_data = tqdm(iterator, desc=f"Train (epoch #{epoch_i} / {self.n_epochs})")
            iterator = tqdm_data

        # steady-state steps 2-4 when the epoch has them; short/debug epochs
        # (the smoke config breaks after one step) trace from step 0 instead
        # of silently capturing nothing
        trace_from = (
            0 if self.debug or len(self.train_dataloader) < 5 else 2
        )
        # xplane capture window (epoch 1 only), refactored onto
        # metrics.trace.XplaneWindow: host spans and the jax.profiler
        # capture mark the same step boundaries
        xplane = (
            XplaneWindow(str(self.trace_dir), start=trace_from, steps=3)
            if self.trace_dir is not None and epoch_i == 1
            else None
        )
        tele = self.telemetry
        tracer = trace_mod.current()
        # either observability plane forces the honest-timing discipline:
        # block on each step's results so 'device' is execution, not
        # dispatch (costs the one-step metric lag; off-path untouched)
        instrument = tele is not None or tracer is not None
        log_every = max(1, int(self.log_every))
        last_consumed = [None]  # last consumed step no (for the final write)

        def consume(values, step_no: int, rows: int) -> None:
            # this device_get blocks until the producing step finishes — by
            # then the NEXT step is already enqueued (see the lag below),
            # so the device never idles on host-side metric/IO work
            host_values = jax.device_get(values)
            for k, v in host_values.items():
                if k == "lr":
                    avg_meters["lr"] = float(v)
                else:
                    # bucketed steps carry bucket-dependent batch sizes and
                    # packed steps row-dependent SEGMENT counts, so the
                    # epoch mean must weight each step's mean by its example
                    # count to stay per-example-correct; plain batches are
                    # equal-sized (weight 1 = historical arithmetic)
                    avg_meters[k].update(float(v), rows if weighted else 1)
            if tele is not None:
                tele.observe_scalars(host_values)
            if self.on_train_metrics is not None:
                self.on_train_metrics(avg_meters, step=step_no)
            last_consumed[0] = step_no
            # writer + progress-bar IO throttled to every `log_every` steps
            # (meters above still integrate every step); the epoch's final
            # state is always written once more in the finally below
            if (step_no + 1) % log_every == 0:
                self._update_writer(avg_meters, prefix="train", step=step_no)
                if tqdm_data is not None:
                    tqdm_data.set_postfix_str(_console_str(avg_meters))

        # Metrics are consumed with a ONE-STEP lag: dispatch step N, then
        # fetch step N-1's scalars while N runs. Without this the per-step
        # device_get serializes device compute with host batch prep.
        # (Bucketed/packed epochs take a data-dependent number of steps <=
        # the sampler length, so the known-total early-drain stays off.)
        lag = LaggedConsumer(
            consume, total=None if weighted else len(self.train_dataloader)
        )

        # instrumented accounting, FIFO-matched to batch order (one worker
        # thread, bounded queue — the prefetcher's ordering guarantee):
        # place() appends, run_step() pops the stats for the batch it runs
        host_stats = deque()
        fetch_wait = [0.0]      # time blocked obtaining the current batch
        host_inline = [True]    # place() ran on the consumer thread?

        def place(batch):
            """Host batch -> placed global arrays + example count (runs on
            the prefetch thread when device_prefetch > 0, inline otherwise —
            same code either way, which is what makes the trajectories
            bit-identical). The count is what the meters weight by: rows
            for plain/bucketed batches, REAL segments for packed ones."""
            t0 = time.perf_counter() if instrument else 0.0
            inputs, labels, meta = self._normalize_batch(batch)
            if isinstance(meta, PackedBatch):
                rows = meta.segments
            elif meta is not None:
                rows = meta.rows
            else:
                rows = int(np.shape(next(iter(inputs.values())))[0])
            if instrument:
                mask = inputs.get("attention_mask")
                real_tokens = int(np.asarray(mask).sum()) if mask is not None else 0
                total_tokens = int(np.asarray(mask).size) if mask is not None else 0
            placed = (
                self._global_batch(self._split_micro(inputs), leading_accum=True),
                self._global_batch(self._split_micro(labels), leading_accum=True),
                rows,
            )
            if instrument:
                t1 = time.perf_counter()
                host_stats.append((t1 - t0, real_tokens, total_tokens))
                if tracer is not None:
                    # emitted from whichever thread ran the placement, so
                    # Perfetto shows prefetch overlap on its own track
                    tracer.complete("place", t0, t1, cat="train")
            return placed

        def timed_fetch(iterator):
            """Yield from ``iterator``, recording per-item blocked time
            (loader wait; + placement when inline) into ``fetch_wait``."""
            iterator = iter(iterator)
            while True:
                t0 = time.perf_counter()
                try:
                    item = next(iterator)
                except StopIteration:
                    return
                t1 = time.perf_counter()
                fetch_wait[0] = t1 - t0
                if tracer is not None:
                    tracer.complete("data_wait", t0, t1, cat="train")
                yield item

        step_i = [0]

        def run_step(placed) -> None:
            dev_inputs, dev_labels, rows = placed
            if xplane is not None:
                xplane.on_step_start(step_i[0])

            t0 = time.perf_counter() if instrument else 0.0
            # store-enabled runs dispatch the AOT executable (a warm
            # restart's first step LOADS it: zero XLA compiles); with the
            # store off the jit wrapper runs exactly as before
            step_fn = (
                self._aot_train_step_program(dev_inputs, dev_labels)
                if aot.get().enabled else self._jit_train_step
            )
            self.params, self.opt_state, values = step_fn(
                self.params, self.opt_state, dev_inputs, dev_labels,
                self.global_step,
            )
            if instrument:
                # StepTimer discipline: block before reading the clock, so
                # 'device' is actual execution time under async dispatch
                jax.block_until_ready(values)
                t1 = time.perf_counter()
                host_s, real_tokens, total_tokens = (
                    host_stats.popleft() if host_stats else (0.0, 0, 0)
                )
                # inline placement runs inside the fetch wait — subtract it
                # so the three components partition the step wall exactly
                wait_s = fetch_wait[0]
                data_wait_s = (
                    max(0.0, wait_s - host_s) if host_inline[0] else wait_s
                )
                fetch_wait[0] = 0.0
                if tracer is not None:
                    tracer.complete(
                        "step", t0, t1, cat="train",
                        args={"step": self.global_step, "rows": rows},
                    )
                if tele is not None:
                    tele.observe_step(
                        self.global_step,
                        data_wait_s=data_wait_s,
                        host_s=host_s,
                        device_s=t1 - t0,
                        examples=rows,
                        real_tokens=real_tokens,
                        total_tokens=total_tokens,
                        # prefetch-thread placement overlaps the previous
                        # step's device time — it is not on the step wall
                        host_overlapped=not host_inline[0],
                    )

            if xplane is not None:
                xplane.on_step_end(step_i[0], values)

            lag.feed(values, self.global_step, rows)
            self.global_step += 1
            step_i[0] += 1
            if self.watchdog is not None:
                self.watchdog.note_progress(self.global_step)

        prefetcher = None
        # one watchdog frame per epoch, re-ticked per step: the deadline
        # covers dataloader/prefetch waits, step dispatch AND the lagged
        # device_get — any of them can be the thing that hangs
        with self._watched(f"train epoch {epoch_i}") as tick:
            try:
                host_iter = iter(iterator)
                interrupted = False
                if not self._preflight_done:
                    # first batch of the run: plan HBM before executing — may
                    # raise batch_split and rebuild the jitted step, so it
                    # must see UNSPLIT host arrays and must happen before the
                    # prefetch thread bakes the old split into placed batches
                    first = next(host_iter, None)
                    if first is not None:
                        _fault("trainer.step")
                        tick(f"train step {self.global_step} (epoch {epoch_i})")
                        inputs, labels, _ = self._normalize_batch(first)
                        self.preflight_train_step(inputs, labels)
                        run_step(place(first))
                        if self.debug:
                            interrupted = True
                if not interrupted and self._prefetch_auto() and (
                    self._prefetch_choice is None
                ):
                    # --device_prefetch auto: time a few steps synchronously
                    # (placement wall vs step wall, first sample discarded as
                    # possibly-compiling) and pick depth 1 vs 2 for the rest
                    # of the run
                    place_s, step_s = [], []
                    for _ in range(_PREFETCH_AUTO_PROBE_STEPS):
                        b = next(host_iter, None)
                        if b is None:
                            break
                        _fault("trainer.step")
                        tick(f"train step {self.global_step} (epoch {epoch_i})")
                        t0 = time.perf_counter()
                        placed = place(b)
                        t1 = time.perf_counter()
                        run_step(placed)
                        jax.block_until_ready(self.params)
                        place_s.append(t1 - t0)
                        step_s.append(time.perf_counter() - t1)
                        if self.debug:
                            interrupted = True
                            break
                    self._prefetch_choice = resolve_prefetch_auto(
                        place_s, step_s
                    )
                    logger.info(
                        "device_prefetch auto: placement %.1f ms vs step "
                        "%.1f ms over %d probe steps -> depth %d.",
                        1e3 * (sum(place_s) / len(place_s)) if place_s else 0,
                        1e3 * (sum(step_s) / len(step_s)) if step_s else 0,
                        len(place_s), self._prefetch_choice,
                    )
                if not interrupted:
                    depth = self._prefetch_depth_static()
                    if depth > 0:
                        prefetcher = DevicePrefetcher(
                            host_iter, place, depth=depth
                        )
                        placed_iter = iter(prefetcher)
                        host_inline[0] = False
                    else:
                        placed_iter = (place(b) for b in host_iter)
                    if instrument:
                        placed_iter = timed_fetch(placed_iter)
                    for placed in placed_iter:
                        _fault("trainer.step")
                        tick(f"train step {self.global_step} (epoch {epoch_i})")
                        run_step(placed)
                        if self.debug:
                            interrupted = True
                            break
                if interrupted:
                    logger.info("Training was interrupted because of debug mode.")
            finally:
                # drain the prefetch thread and flush the metric lag even on
                # a mid-epoch exception/SIGTERM — without this the last
                # steps' metrics (and the trace/writer below) are silently
                # dropped on any non-clean epoch exit
                close_err = None
                if prefetcher is not None:
                    try:
                        prefetcher.close()
                    except BaseException as e:  # noqa: BLE001
                        # close() raises only on a CLEAN exit with a wedged
                        # thread (it just warns when an exception is already
                        # propagating) — hold it until the flushes ran
                        close_err = e
                lag.flush()

                if xplane is not None:  # close a window still open mid-epoch
                    xplane.abort(self.params)

                if last_consumed[0] is not None and (
                    (last_consumed[0] + 1) % log_every != 0
                ):
                    # final throttled write so the epoch always ends with
                    # current meters on the writer/progress bar
                    self._update_writer(
                        avg_meters, prefix="train", step=last_consumed[0]
                    )
                    if tqdm_data is not None:
                        tqdm_data.set_postfix_str(_console_str(avg_meters))

                if weighted and self.train_dataloader.epoch_stats:
                    stats = self.train_dataloader.epoch_stats
                    if packed:
                        logger.info(
                            "Packed epoch %d: %d batches, packing "
                            "efficiency %.2f%% (padding waste %.2f%%; "
                            "pad-to-max would waste %.2f%%; %d splits in "
                            "%d fragment rows).",
                            epoch_i, stats["batches"],
                            100.0 * stats.get("packing_efficiency", 0.0),
                            stats.get("padding_waste_pct", 0.0),
                            stats.get("padmax_waste_pct", 0.0),
                            stats.get("split_count", 0),
                            stats.get("fragment_rows", 0),
                        )
                    else:
                        logger.info(
                            "Bucketed epoch %d: %d batches, padding waste "
                            "%.2f%% (pad-to-max would be %.2f%%).",
                            epoch_i, stats["batches"],
                            stats.get("padding_waste_pct", 0.0),
                            stats.get("padmax_waste_pct", 0.0),
                        )
                    # the LR schedule is sized from the loader's PLANNED
                    # step count (a length-only simulation of the packer/
                    # bucketer — _plan_schedule_steps) rather than the old
                    # len(dataset)/batch upper bound; the warning now only
                    # fires when the ACTUAL epoch undershoots even that
                    # plan (stochastic chunk lengths drifting, mid-epoch
                    # abort), so it flags real schedule stretch instead of
                    # the planner's known overshoot
                    estimate = (
                        self._planned_steps_per_epoch
                        if self._planned_steps_per_epoch is not None
                        else len(self.train_dataloader)
                    )
                    if epoch_i == 1 and stats["batches"] < 0.8 * estimate:
                        logger.warning(
                            "Epoch took %d steps vs the %d-step schedule "
                            "estimate: the LR decay will end ~%.0f%% early "
                            "(warmup stretched accordingly). Consider "
                            "raising n_epochs or lowering warmup_coef.",
                            stats["batches"], estimate,
                            100.0 * (1.0 - stats["batches"] / estimate),
                        )

                if self.writer is not None:
                    self.writer.flush()  # survive preemption with events intact
                if close_err is not None:
                    raise close_err

    # -- test loop (trainer.py:302-353) ----------------------------------------

    def test(self, epoch_i, *, callbacks=None):
        if self.test_dataloader is None:
            logger.warning("No test dataset was provided; test() is a no-op.")
            return None

        if callbacks is not None and not isinstance(callbacks, (list, tuple)):
            callbacks = (callbacks,)
        if callbacks is not None:
            assert all(isinstance(c, TestCallback) for c in callbacks)

        # eval wall time is badput under the goodput discipline (chips
        # busy, no training progress): hand it to the ledger via telemetry
        t0 = time.perf_counter()
        try:
            with self.mesh:
                return self._test(epoch_i, callbacks=callbacks)
        finally:
            if self.telemetry is not None:
                self.telemetry.observe_eval(time.perf_counter() - t0)

    @time_profiler
    def _test(self, epoch_i, *, callbacks=None):
        if self._jit_eval_step is None:
            self._jit_eval_step = self._build_eval_step()

        avg_meters: dict = defaultdict(AverageMeter)
        bucketed = isinstance(self.test_dataloader, BucketedDataLoader)
        packed = isinstance(self.test_dataloader, PackedDataLoader)

        iterator = self.test_dataloader
        tqdm_data = None
        if tqdm is not None:
            tqdm_data = tqdm(
                self.test_dataloader, desc=f"Test (epoch #{epoch_i} / {self.n_epochs})"
            )
            iterator = tqdm_data

        def consume(i, labels, dev_labels, preds, values, meta) -> None:
            # blocks on batch i's results — batch i+1 is already enqueued
            # (same one-step-lag pipelining as the train loop)
            if isinstance(meta, PackedBatch):
                # packed eval: the device loss is already a mean over REAL
                # segments only (PackedWeightedLoss keys every head on
                # segment_mask, and pad rows carry zero mask), so no
                # partial-batch recompute is needed; callbacks receive the
                # per-chunk arrays scattered out of the [rows, S] segment
                # planes through the packing map (row-major segment order)
                n_valid = meta.segments
                host_values = jax.device_get(values)
                for k, v in host_values.items():
                    avg_meters[k].update(float(v), n_valid)
                if callbacks is not None:
                    host_preds = gather_to_host(preds)
                    host_labels = (
                        labels if self.process_count == 1
                        else gather_to_host(dev_labels)
                    )
                    m = np.asarray(host_labels["segment_mask"]).reshape(-1) > 0
                    host_preds = {
                        k: np.asarray(v).reshape(
                            (-1,) + np.asarray(v).shape[2:]
                        )[m]
                        for k, v in host_preds.items()
                    }
                    host_labels = {
                        k: np.asarray(v).reshape(-1)[m]
                        for k, v in host_labels.items()
                        if k != "segment_mask"
                    }
                    for callback in callbacks:
                        callback.at_iteration_end(
                            host_preds, host_labels, avg_meters
                        )
                if tqdm_data is not None:
                    tqdm_data.set_postfix_str(_console_str(avg_meters))
                return
            if meta is not None:  # bucketed batch carries its own row count
                n_valid = meta.real_rows
                batch_rows = meta.rows
            else:
                n_valid = self.test_dataloader.real_rows(i)
                batch_rows = self._test_sampler.global_batch_size
            is_partial = n_valid < batch_rows

            host_preds = host_labels = None
            if callbacks is not None or is_partial:
                host_preds = gather_to_host(preds)
                host_labels = (
                    labels if self.process_count == 1 else gather_to_host(dev_labels)
                )
                # trim padding rows of the final partial batch
                host_preds = {k: v[:n_valid] for k, v in host_preds.items()}
                host_labels = {k: np.asarray(v)[:n_valid] for k, v in host_labels.items()}

            if is_partial:
                # the device loss averaged over pad-duplicated rows; recompute
                # on the trimmed batch so meters see only real examples
                _, values_ = self.loss(
                    {k: jnp.asarray(v) for k, v in host_preds.items()},
                    {k: jnp.asarray(v) for k, v in host_labels.items()},
                )
            else:
                values_ = values

            host_values = jax.device_get(values_)
            for k, v in host_values.items():
                # weight by REAL rows: pad_last repetition rows carry zero
                # weight, and bucketed batches of different sizes contribute
                # per-example-correctly to the epoch mean
                avg_meters[k].update(float(v), n_valid)

            if callbacks is not None:
                for callback in callbacks:
                    callback.at_iteration_end(host_preds, host_labels, avg_meters)

            if tqdm_data is not None:
                tqdm_data.set_postfix_str(_console_str(avg_meters))

        # bucketed/packed epochs take a data-dependent number of batches, so
        # the known-total early drain stays off there (flush() covers the
        # tail)
        lag = LaggedConsumer(
            consume,
            total=None if (bucketed or packed) else len(self.test_dataloader),
        )

        def place_eval(batch):
            """Host batch -> (host labels, placed inputs/labels, meta); runs
            on the prefetch thread when device_prefetch > 0."""
            inputs, labels, meta = self._normalize_batch(batch)
            return (
                labels,
                self._global_batch(inputs),
                self._global_batch(labels),
                meta,
            )

        prefetcher = None
        eval_depth = self._prefetch_depth_static()
        if eval_depth > 0:
            prefetcher = DevicePrefetcher(
                iter(iterator), place_eval, depth=eval_depth,
                name="device-prefetch-eval",
            )
            placed_iter = iter(prefetcher)
        else:
            placed_iter = (place_eval(b) for b in iterator)

        with self._watched(f"test epoch {epoch_i}") as tick:
            try:
                for i, (labels, dev_inputs, dev_labels, meta) in enumerate(placed_iter):
                    _fault("trainer.eval_step")
                    tick(f"eval step {i} (epoch {epoch_i})")

                    preds, values = self._jit_eval_step(self.params, dev_inputs, dev_labels)

                    lag.feed(i, labels, dev_labels, preds, values, meta)

                    if self.debug and i >= 10:
                        logger.info("Test was interrupted because of debug mode.")
                        break
            finally:
                # same mid-epoch guarantees as _train: drain the prefetch
                # thread and flush the metric lag even on exception/SIGTERM
                # (close() raises only on a clean exit with a wedged thread;
                # hold that until the in-flight batches have been consumed)
                close_err = None
                if prefetcher is not None:
                    try:
                        prefetcher.close()
                    except BaseException as e:  # noqa: BLE001
                        close_err = e
                lag.flush()
                if close_err is not None:
                    raise close_err

        if callbacks is not None:
            for callback in callbacks:
                callback.at_epoch_end(avg_meters, self)

        self._update_writer(avg_meters, prefix="test")
        if self.writer is not None:
            self.writer.flush()

        metrics = {
            k: v() if isinstance(v, AverageMeter) else v for k, v in avg_meters.items()
        }
        logger.info(f"Test metrics after epoch {epoch_i} - {_console_str(metrics)}")
        return metrics

    # -- checkpointing (trainer.py:355-403) ------------------------------------

    def _bundle_ls(self):
        """Wrap a freshly initialized ``opt_state`` with a fresh scaling
        state when loss scaling is on (no-op otherwise)."""
        if not self._use_loss_scale:
            return
        ls_state = ls_lib.init_state(self._ls_init_scale, dynamic=self._ls_dynamic)
        if not is_single_device(self.mesh):
            ls_state = self.plan.put_replicated(ls_state)
        self.opt_state = ls_lib.OptStateWithLS(self.opt_state, ls_state)

    def _split_ls(self):
        """Live ``(opt_state, ls_state)``; ls_state is None when scaling is off."""
        if isinstance(self.opt_state, ls_lib.OptStateWithLS):
            return self.opt_state.inner, self.opt_state.ls
        return self.opt_state, None

    def _checkpoint_extra(self) -> dict:
        """Topology record every checkpoint carries: the actual optimizer
        layout and the plan's mesh axes — so ``peek_checkpoint_layout``
        can report what topology wrote a checkpoint (restores stay
        shape-driven and reshard onto any live plan). Pipeline runs
        additionally stamp the tick schedule and whether the trunk was
        stored stage-local (``stage``) or replicated per rank — purely
        informational for the peek: both restore paths are shape-driven,
        so a stage-sharded save at ``pipe:K`` restores at ``pipe:K'``,
        under no pipe axis at all, or under the other schedule."""
        pipe = self.pipe_stages > 1
        return {
            "opt_sharding": self.effective_opt_sharding,
            "mesh_axes": self.plan.describe(),
            "pipe_schedule": self.pipe_schedule if pipe else None,
            "pipe_param_layout": (
                ("stage" if self._stage_param_specs is not None
                 else "replicated") if pipe else None
            ),
        }

    def save_state_dict(self, path_):
        if self.debug:
            logger.info(f"Model was not saved to {path_} because of debug mode.")
            return
        if self._async_ckpt is not None and self._async_supported():
            return self._save_state_dict_async(path_)
        if self._async_ckpt is not None:
            # sync fallback still honors the single-flight contract: a
            # previous async persist must land before this save writes
            self.finish_pending_checkpoint()
        opt_state, ls_state = self._split_ls()
        # its own watchdog frame: the sharded save crosses process barriers,
        # and a peer that died mid-save must abort this host (for restart)
        # rather than park it on the barrier forever. 8x the step timeout:
        # a save legitimately gathers/writes the FULL state (the non-sharded
        # path in particular), which dwarfs a step — a slow save must not be
        # misclassified as a hang and crash-looped. Barriers inside inherit
        # this budget (watchdog.arm nested-frame default).
        extra = self._checkpoint_extra()
        t0 = time.perf_counter()
        with self._watched(f"checkpoint save {path_}", scale=8.0), \
                trace_mod.span("checkpoint_save", cat="train",
                               args={"path": str(path_),
                                     "step": self.global_step}):
            if self.sharded_checkpoint:
                from .checkpoint import save_state_dict_sharded

                save_state_dict_sharded(
                    path_,
                    params=self.params,
                    opt_state=opt_state,
                    loss_scale=ls_state,
                    global_step=self.global_step,
                    extra=extra,
                )
            else:
                _save_ckpt(
                    path_,
                    params=self.params,
                    opt_state=opt_state,
                    loss_scale=ls_state,
                    global_step=self.global_step,
                    is_primary=self.is_primary,
                    extra=extra,
                )
        if self.telemetry is not None:
            self.telemetry.observe_checkpoint_save(time.perf_counter() - t0)

    def _async_supported(self) -> bool:
        """Async persist is restricted to configurations whose persist leg
        is free of cross-process DEVICE collectives: a multi-host SHARDED
        persist runs ``sync_global_devices`` barriers, and issuing those
        from a background thread concurrently with the main thread's
        train-step collectives can reorder collective launches across
        hosts (pod deadlock) — and would arm watchdog frames on the
        process-global LIFO stack from the wrong thread. Single-process
        sharded persists skip the barriers entirely, and single-file
        persists never had any; multi-host sharded saves fall back to the
        sync path with a (once) log line."""
        if not (self.sharded_checkpoint and self.process_count > 1):
            return True
        if not getattr(self, "_async_fallback_logged", False):
            self._async_fallback_logged = True
            logger.warning(
                "--async_checkpoint with --sharded_checkpoint on a "
                "multi-host world: the sharded persist crosses process "
                "barriers, which must not run on a background thread "
                "concurrently with training collectives — saving "
                "synchronously instead."
            )
        return False

    def _save_state_dict_async(self, path_):
        """Async overlapped save (--async_checkpoint): block only for the
        device->host snapshot (plus the completion barrier on any previous
        persist), then serialize+write on the background thread with the
        same crc32/atomic-rename discipline a sync save uses. The snapshot
        deep-copies every leaf (``copy=True``) because the very next train
        step DONATES the live buffers the gather would otherwise view."""
        from .checkpoint import (
            persist_state,
            persist_state_sharded,
            snapshot_state,
            snapshot_state_sharded,
        )

        opt_state, ls_state = self._split_ls()
        extra = self._checkpoint_extra()
        t0 = time.perf_counter()
        with self._watched(f"checkpoint save {path_}", scale=8.0), \
                trace_mod.span("checkpoint_save", cat="train",
                               args={"path": str(path_),
                                     "step": self.global_step,
                                     "async": True}):
            # completion barrier BEFORE snapshotting anew: two persists
            # must never interleave on one path, and a failed background
            # persist surfaces here, not silently
            self._async_ckpt.wait()
            with trace_mod.span("ckpt_snapshot", cat="train",
                                args={"step": self.global_step}):
                if self.sharded_checkpoint:
                    snap = snapshot_state_sharded(
                        params=self.params, opt_state=opt_state,
                        loss_scale=ls_state, global_step=self.global_step,
                        extra=extra, copy=True,
                    )
                    persist = functools.partial(
                        persist_state_sharded, os.fspath(path_), snap
                    )
                else:
                    state = snapshot_state(
                        params=self.params, opt_state=opt_state,
                        loss_scale=ls_state, global_step=self.global_step,
                        extra=extra, is_primary=self.is_primary, copy=True,
                    )
                    persist = (
                        None if state is None
                        else functools.partial(
                            persist_state, os.fspath(path_), state
                        )
                    )
        blocking = time.perf_counter() - t0
        if self.telemetry is not None:
            self.telemetry.observe_checkpoint_snapshot(blocking)
        if persist is not None:
            on_done = (
                self.telemetry.observe_checkpoint_persist
                if self.telemetry is not None else None
            )
            self._async_ckpt.submit(path_, persist, on_done=on_done)
            logger.info(
                "Async checkpoint: step %d snapshot blocked %.3fs; persist "
                "to %s running in the background.",
                self.global_step, blocking, path_,
            )

    def finish_pending_checkpoint(self, *, raise_errors: bool = True) -> None:
        """Completion barrier for --async_checkpoint: block until the
        in-flight background persist lands (no-op when async checkpointing
        is off or idle). Must run before process exit and before a
        checkpoint is handed to the supervisor for resume (the SIGTERM
        path). ``raise_errors=False`` is for best-effort paths (an
        exception already propagating, or an emergency save that a STALE
        failure must not abort): the failure is logged at ERROR and
        CONSUMED — a later barrier will not re-raise it."""
        if self._async_ckpt is None:
            return
        with self._watched("checkpoint persist wait", scale=8.0):
            self._async_ckpt.wait(raise_errors=raise_errors)

    def _warn_topology_change(self, path_) -> None:
        """Name an elastic (or manual) topology change at restore time.

        Sharded directories record the saver's ``mesh_axes`` in the
        manifest; when they differ from the live plan the restore is a
        cross-topology reshard — legitimate and supported (crop/zero-fill
        reconciliation plus shape-driven resharding), but it must be LOUD:
        the operator reading this log is deciding whether a shrunk pod is
        still the run they want. Single-file checkpoints are skipped —
        peeking one costs a full deserialize and they are replicated
        saves, so there is no sharded layout to mismatch."""
        if not os.path.isdir(os.fspath(path_)):
            return
        from .checkpoint import peek_checkpoint_layout

        layout = peek_checkpoint_layout(path_)
        saved = (layout or {}).get("mesh_axes")
        live = self.plan.describe()
        if not saved or dict(saved) == live:
            return
        logger.warning(
            f"ELASTIC RESUME / topology change: checkpoint {path_} was "
            f"saved under mesh {dict(saved)}, restoring onto {live}. "
            f"Optimizer state is corner-cropped/zero-filled onto the live "
            f"ZeRO-1 layout; the LR schedule is keyed to the GLOBAL batch "
            f"and global_step, so it continues unchanged — at a smaller "
            f"data axis each step consumes the same global batch over "
            f"fewer devices (slower wall-clock, identical math)."
        )
        if self.telemetry is not None:
            flightrec = getattr(self.telemetry, "flightrec", None)
            if flightrec is not None:
                flightrec.record("mesh_shrunk", old=dict(saved), new=live)

    def load_state_dict(self, path_):
        if self._async_ckpt is not None:
            # a restore must observe the last save durably on disk (and a
            # background persist failure must surface before training
            # resumes from possibly-stale state)
            self.finish_pending_checkpoint()
        t0 = time.perf_counter()
        live_opt, live_ls = self._split_ls()
        with trace_mod.span("checkpoint_restore", cat="train",
                            args={"path": str(path_)}):
            params, opt_state, ls_state, global_step = _load_ckpt(
                path_,
                params=self.params,
                opt_state=live_opt,
                loss_scale=live_ls,
                drop_optimizer=self.drop_optimizer,
            )
        if self.telemetry is not None:
            self.telemetry.observe_checkpoint_restore(
                time.perf_counter() - t0)
        if global_step is None:
            return
        self._warn_topology_change(path_)
        if not self.drop_optimizer and live_opt is not None and opt_state is not None:
            # mesh-shape / sharding-mode portability: crop/zero-fill each
            # restored leaf onto the LIVE (possibly differently padded)
            # zero1 layout before re-placement — a save at mesh N resumes
            # at mesh M and across --optimizer_sharding modes
            opt_state = reconcile_state_shapes(opt_state, live_opt)
        if live_ls is not None:
            mode_differs = bool(ls_state.dynamic) != bool(live_ls.dynamic)
            static_value_differs = (
                not bool(live_ls.dynamic)
                and float(ls_state.scale) != float(live_ls.scale)
            )
            if ls_state is not live_ls and (mode_differs or static_value_differs):
                # the flag is CONFIG: neither the mode nor a static value may
                # be silently overridden by what a checkpoint happened to
                # contain — keep the freshly configured state
                logger.warning(
                    "Checkpoint loss-scale state differs from --apex_loss_scale; "
                    "keeping the configured scaling state."
                )
                ls_state = live_ls
            opt_state = ls_lib.OptStateWithLS(opt_state, ls_state)
        # re-place restored host values with the original shardings
        if self._param_shardings is None:
            self.params = shard_params(params, self.mesh)
            if not self.drop_optimizer and self.opt_state is not None:
                from ..parallel.sharding import put_single

                self.opt_state = jax.tree_util.tree_map(
                    lambda x: put_single(x, self.mesh), opt_state
                )
        else:
            # Restored host state goes through a jitted identity with
            # explicit out_shardings, NOT a plain device_put: on the CPU
            # runtime device_put zero-copies a host numpy buffer without
            # keeping it alive, the train step then DONATES that buffer,
            # and the next step reads freed memory (observed as heap
            # corruption on every resume-then-train on the virtual
            # multi-device mesh; msgpack-restored leaves are additionally
            # read-only views into the checkpoint blob, which donation
            # must never write into). The jit identity copies every leaf
            # into runtime-owned buffers in one compiled program.
            self.params = jax.jit(
                lambda x: x, out_shardings=self._param_shardings
            )(params)
            if not self.drop_optimizer and self.opt_state is not None:
                shardings = jax.tree_util.tree_map(lambda x: x.sharding, self.opt_state)
                self.opt_state = jax.jit(
                    lambda x: x, out_shardings=shardings
                )(opt_state)
        self.global_step = global_step
