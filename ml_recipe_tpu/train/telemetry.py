"""Training-plane telemetry: the trainer's /metrics surface.

The serving plane has had a first-party Prometheus registry since PR 3;
the training plane — the thing that runs for days on pod slices — was
observable only through tqdm postfix lines and the TensorBoard writer.
:class:`TrainTelemetry` gives it the same surface: a registry of training
metrics (served by ``metrics.exporter.MetricsExporter`` from
``--metrics_port``) fed per consumed step by the trainer with a wall-time
breakdown —

- ``data_wait``: blocked on the loader / prefetch queue,
- ``host``: collate + micro split + host→device placement,
- ``device``: step dispatch + device execution (the ``StepTimer``
  block-until-ready discipline, so async dispatch cannot fake it),

plus tokens/sec, padding waste, loss-scale adjustments, checkpoint
save/restore durations, and — at scrape time — the watchdog heartbeat age
and the supervisor's restart/exit-classification counts read cross-process
from its JSON sidecar (``resilience.supervisor.peek_supervisor_state``).

Everything here is opt-in and host-side-only: with no telemetry attached
the trainer's step loop is bit-identical to the untelemetered path, and
with it attached only timing/blocking changes — never batch contents,
order, or arithmetic.
"""

from __future__ import annotations

import logging
import math
from typing import Dict, Optional

from ..metrics.anomaly import AnomalyReport, SlowStepDetector
from ..metrics.registry import Registry

logger = logging.getLogger(__name__)

# step-scale histogram bounds: 5 ms .. 120 s (a pod-scale step with a
# checkpoint barrier in the tail is seconds, not the serving plane's ms)
STEP_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    120.0,
)

# checkpoint I/O is far slower than a step: 50 ms .. 10 min
CKPT_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0, 600.0)


class TrainTelemetry:
    """Registry + per-step accounting + slow-step anomaly detection."""

    def __init__(
        self,
        *,
        registry: Optional[Registry] = None,
        process_index: int = 0,
        process_count: int = 1,
        anomaly_factor: float = 3.0,
        anomaly_window: int = 64,
        anomaly_min_steps: int = 8,
        watchdog=None,
        supervisor_state_path=None,
        goodput=None,
        flightrec=None,
    ):
        self.registry = registry if registry is not None else Registry()
        self.watchdog = watchdog
        self.supervisor_state_path = (
            str(supervisor_state_path) if supervisor_state_path else None
        )
        # run-level accounting plane (PR 13), both optional and host-only:
        # goodput is a metrics.goodput.GoodputLedger (productive-vs-badput
        # wall-clock partition, exported as train_goodput_ratio), flightrec
        # a metrics.flightrec.FlightRecorder (last-N-events crash timeline)
        self.goodput = goodput
        self.flightrec = flightrec
        self._observed_steps = 0
        self._aot_hits = 0
        self._aot_misses = 0
        self.detector = SlowStepDetector(
            factor=anomaly_factor,
            window=anomaly_window,
            min_steps=anomaly_min_steps,
        )
        self._last_loss_scale: Optional[float] = None

        m = self.registry
        self.m_steps = m.counter(
            "train_steps_total", "Consumed optimizer steps this process.")
        self.m_global_step = m.gauge(
            "train_global_step", "Current global optimizer step.")
        self.m_step = m.histogram(
            "train_step_seconds",
            "Per-step wall time: data wait + host + device (host excluded "
            "when the prefetch thread overlaps it with device compute).",
            STEP_BUCKETS)
        self.m_data_wait = m.histogram(
            "train_step_data_wait_seconds",
            "Per-step time blocked on the loader / prefetch queue.",
            STEP_BUCKETS)
        self.m_host = m.histogram(
            "train_step_host_seconds",
            "Per-step collate + micro split + host-to-device placement time.",
            STEP_BUCKETS)
        self.m_device = m.histogram(
            "train_step_device_seconds",
            "Per-step dispatch + device execution time (block-until-ready).",
            STEP_BUCKETS)
        self.m_tokens_per_sec = m.gauge(
            "train_tokens_per_sec",
            "Real (non-pad) input tokens per second, last consumed step.")
        self.m_examples_per_sec = m.gauge(
            "train_examples_per_sec",
            "Examples (rows / packed segments) per second, last step.")
        self.m_padding_waste = m.gauge(
            "train_padding_waste_pct",
            "Share of step input tokens that are padding, last step (%).")
        self.m_loss = m.gauge(
            "train_loss", "Running mean training loss (epoch meter).")
        self.m_lr = m.gauge(
            "train_lr", "Learning rate at the last consumed step.")
        self.m_loss_scale = m.gauge(
            "train_loss_scale",
            "Current loss scale (0 when loss scaling is off).")
        self.m_loss_scale_adjustments = m.counter(
            "train_loss_scale_adjustments_total",
            "Dynamic loss-scale changes (growth or overflow backoff).")
        self.m_slow_steps = m.counter(
            "train_slow_steps_total",
            "Steps flagged anomalous by the rolling median+MAD detector.")
        self.m_ckpt_save = m.histogram(
            "train_checkpoint_save_seconds",
            "Checkpoint save durations on the step critical path (sync "
            "saves: full serialize+write; async saves: the blocking "
            "device-to-host snapshot only).", CKPT_BUCKETS)
        self.m_ckpt_persist = m.histogram(
            "train_checkpoint_persist_seconds",
            "Background persist durations of async checkpoint saves "
            "(serialize+write overlapped with training — off the step "
            "critical path).", CKPT_BUCKETS)
        self.m_ckpt_restore = m.histogram(
            "train_checkpoint_restore_seconds",
            "Checkpoint restore durations.", CKPT_BUCKETS)
        self.m_zero1_buckets = m.gauge(
            "train_zero1_buckets",
            "Gradient buckets in the bucketed ZeRO-1 collective-overlap "
            "plan (0 = monolithic exchange / overlap off).")
        self.m_aot_hits = m.counter(
            "train_aot_cache_hits_total",
            "AOT program-store loads that replaced an XLA compile "
            "(ops/aot.py: zero-compile warm restarts).")
        self.m_aot_misses = m.counter(
            "train_aot_cache_misses_total",
            "AOT program-store misses: programs compiled (and persisted "
            "for the next restart).")
        self.m_aot_load = m.histogram(
            "train_aot_load_seconds",
            "AOT program load (deserialize) times on store hits.",
            STEP_BUCKETS)
        self.m_heartbeat_age = m.gauge(
            "train_watchdog_heartbeat_age_seconds",
            "Seconds since the step watchdog last saw progress "
            "(-1: no watchdog armed).")
        self.m_sup_restarts = m.gauge(
            "train_supervisor_restarts",
            "Supervisor restart budget consumed (no-progress failures), "
            "from the supervisor JSON sidecar (-1: no sidecar).")
        self.m_sup_attempts = m.gauge(
            "train_supervisor_attempts",
            "Supervisor attempts launched so far (-1: no sidecar).")
        self.m_sup_preempted = m.gauge(
            "train_supervisor_exits_preempted",
            "Child exits the supervisor classified as preemptions.")
        self.m_sup_hang = m.gauge(
            "train_supervisor_exits_hang",
            "Child exits the supervisor classified as hangs "
            "(watchdog aborts).")
        self.m_sup_crash = m.gauge(
            "train_supervisor_exits_crash",
            "Child exits the supervisor classified as crashes.")
        self.m_goodput = m.gauge(
            "train_goodput_ratio",
            "Productive step time / total run wall-clock, from the goodput "
            "ledger (-1: no ledger attached).")
        self.m_badput = m.labeled_gauge(
            "train_badput_seconds_total",
            "Non-productive run wall-clock by category, from the goodput "
            "ledger (compile_warmup / data_wait / checkpoint_save / "
            "checkpoint_restore / eval / restart_downtime / recompute / "
            "other).",
            "category")
        self.m_process = m.info(
            "train_process_info",
            "Identity of this training process on the mesh.",
            {
                "process_index": str(process_index),
                "process_count": str(process_count),
            },
        )
        self.m_heartbeat_age.set(-1.0)
        self.m_sup_restarts.set(-1.0)
        self.m_sup_attempts.set(-1.0)
        self.m_goodput.set(-1.0)

    # -- per-step feed (train loop) --------------------------------------------

    def observe_step(
        self,
        step: int,
        *,
        data_wait_s: float,
        host_s: float,
        device_s: float,
        examples: int = 0,
        real_tokens: int = 0,
        total_tokens: int = 0,
        host_overlapped: bool = False,
    ) -> Optional[AnomalyReport]:
        """Feed one consumed step's breakdown; total step time is defined
        as the sum of the components on the critical path (pinned by the
        accounting test). ``host_overlapped=True`` (the device-prefetch
        path) excludes ``host_s`` from the total and the detector
        baseline: placement ran on the prefetch thread UNDER the previous
        step's device time, so counting it would overstate the step wall
        — a prefetch thread that falls behind surfaces as data wait. The
        host histogram itself still records every placement. Returns the
        anomaly report when the detector fired (already logged and counted
        here)."""
        total = data_wait_s + device_s
        breakdown = {"data_wait": data_wait_s, "device": device_s}
        if not host_overlapped:
            total += host_s
            breakdown["host"] = host_s
        self.m_steps.inc()
        self.m_global_step.set(step)
        self.m_step.observe(total)
        self.m_data_wait.observe(data_wait_s)
        self.m_host.observe(host_s)
        self.m_device.observe(device_s)
        if total > 0:
            if real_tokens:
                self.m_tokens_per_sec.set(real_tokens / total)
            if examples:
                self.m_examples_per_sec.set(examples / total)
        if total_tokens:
            self.m_padding_waste.set(
                100.0 * (1.0 - real_tokens / total_tokens))

        # goodput ledger: the first observed step carries compilation —
        # its non-wait share is compile/warmup badput, not productive time.
        # The aot_hit flag says whether that warmup was a store LOAD
        # (every observed program-store decision a hit) or a real compile
        first = self._observed_steps == 0
        self._observed_steps += 1
        if self.goodput is not None:
            aot_hit = None
            if first and (self._aot_hits or self._aot_misses):
                aot_hit = self._aot_misses == 0
            self.goodput.note_step(
                step, wall_s=total, data_wait_s=data_wait_s, compile=first,
                aot_hit=aot_hit,
            )

        report = self.detector.update(step, total, breakdown)
        if report is not None:
            self.m_slow_steps.inc()
            logger.warning(report.message())

        if self.flightrec is not None:
            heartbeat = (
                self.watchdog.heartbeat_age()
                if self.watchdog is not None else None
            )
            self.flightrec.record(
                "step", step=int(step), total_s=round(total, 6),
                data_wait_s=round(data_wait_s, 6),
                host_s=round(host_s, 6), device_s=round(device_s, 6),
                examples=int(examples),
                heartbeat_age_s=(
                    round(heartbeat, 3) if heartbeat is not None else None
                ),
            )
            if report is not None:
                # the anomaly verdict rides the ring too: attribution must
                # survive the crash that often follows a stall
                self.flightrec.record(
                    "slow_step", step=report.step,
                    total_s=round(report.total_s, 6),
                    threshold_s=round(report.threshold_s, 6),
                    attribution=report.attribution,
                    component_s=round(report.component_s, 6),
                )
        return report

    def observe_aot(self, outcome: str, seconds: float) -> None:
        """One AOT program-store decision from the trainer's routing
        (ops/aot.py): ``'hit'`` = deserialized (the load-time histogram
        records it), ``'miss'`` = compiled. Bypass decisions (store off)
        never reach here."""
        if outcome == "hit":
            self._aot_hits += 1
            self.m_aot_hits.inc()
            self.m_aot_load.observe(seconds)
        elif outcome == "miss":
            self._aot_misses += 1
            self.m_aot_misses.inc()
        if self.flightrec is not None:
            self.flightrec.record(
                "aot", outcome=outcome, seconds=round(seconds, 6))

    def observe_scalars(self, host_values: Dict[str, float]) -> None:
        """Per-consumed-step scalar taps from the train step's host fetch
        (loss, lr, loss scale)."""
        loss = host_values.get("loss")
        if loss is not None:
            value = float(loss)
            if math.isfinite(value):
                self.m_loss.set(value)
        lr = host_values.get("lr")
        if lr is not None:
            self.m_lr.set(float(lr))
        scale = host_values.get("loss_scale")
        if scale is not None:
            value = float(scale)
            self.m_loss_scale.set(value)
            if (
                self._last_loss_scale is not None
                and value != self._last_loss_scale
            ):
                self.m_loss_scale_adjustments.inc()
                if self.flightrec is not None:
                    self.flightrec.record(
                        "loss_scale", scale=value,
                        previous=self._last_loss_scale,
                    )
            self._last_loss_scale = value

    # -- checkpoint + scrape-time feeds ----------------------------------------

    def observe_checkpoint_save(self, seconds: float) -> None:
        self.m_ckpt_save.observe(seconds)
        if self.goodput is not None:
            self.goodput.note_checkpoint("save", seconds)
        if self.flightrec is not None:
            self.flightrec.record(
                "checkpoint_save", seconds=round(seconds, 6))

    def observe_checkpoint_snapshot(self, seconds: float) -> None:
        """Blocking leg of an ASYNC save (device->host snapshot + the
        wait for any previous persist): this IS the save's critical-path
        cost, so it feeds the same save histogram and checkpoint_save
        badput the sync path does — the async win shows up as this number
        shrinking while the persist time moves to the overlapped feed."""
        self.m_ckpt_save.observe(seconds)
        if self.goodput is not None:
            self.goodput.note_checkpoint("save", seconds)
        if self.flightrec is not None:
            self.flightrec.record(
                "ckpt_snapshot", seconds=round(seconds, 6))

    def observe_checkpoint_persist(self, seconds: float,
                                   stalled_s: float = 0.0) -> None:
        """Background leg of an async save (serialize + write, called
        from the persist thread on completion). Only the share that ran
        while training proceeded is ledgered as checkpoint_overlapped_s:
        ``stalled_s`` — time the main thread spent blocked waiting on
        this persist (the next save's barrier, a restore, exit) — is
        already on the critical path and booking it as overlap would
        overstate the async win by exactly the stall."""
        self.m_ckpt_persist.observe(seconds)
        if self.goodput is not None:
            self.goodput.note_checkpoint(
                "save", max(0.0, seconds - stalled_s), overlapped=True
            )
        if self.flightrec is not None:
            self.flightrec.record(
                "ckpt_persist", seconds=round(seconds, 6),
                stalled_s=round(stalled_s, 6))

    def observe_zero1_buckets(self, buckets) -> None:
        """Record the bucketed ZeRO-1 overlap plan (a list of
        ``GradBucket``): the bucket count rides /metrics and the per-
        bucket byte layout lands in the flight recorder, so a post-mortem
        can attribute a collective stall to its bucket."""
        buckets = list(buckets or [])
        self.m_zero1_buckets.set(float(len(buckets)))
        if self.flightrec is not None and buckets:
            self.flightrec.record(
                "zero1_bucket_plan",
                buckets=len(buckets),
                leaf_ranges=[[int(b.lo), int(b.hi)] for b in buckets],
                bucket_bytes=[int(b.nbytes) for b in buckets],
            )

    def observe_checkpoint_restore(self, seconds: float) -> None:
        self.m_ckpt_restore.observe(seconds)
        if self.goodput is not None:
            self.goodput.note_checkpoint("restore", seconds)
        if self.flightrec is not None:
            self.flightrec.record(
                "checkpoint_restore", seconds=round(seconds, 6))

    def observe_eval(self, seconds: float) -> None:
        """One eval epoch's wall time — badput under the goodput
        discipline (chips busy, no training progress)."""
        if self.goodput is not None:
            self.goodput.note_eval(seconds)
        if self.flightrec is not None:
            self.flightrec.record("eval", seconds=round(seconds, 6))

    def refresh(self) -> None:
        """Scrape-time gauges: watchdog heartbeat age, goodput accounting
        + supervisor sidecar (registered as the exporter's pre-render
        hook)."""
        age = None
        if self.watchdog is not None:
            age = self.watchdog.heartbeat_age()
        self.m_heartbeat_age.set(age if age is not None else -1.0)

        if self.goodput is not None:
            summary = self.goodput.summary()
            ratio = summary["goodput_ratio"]
            self.m_goodput.set(ratio if ratio is not None else -1.0)
            for category, seconds in summary["badput_s"].items():
                self.m_badput.set(category, seconds)

        if self.supervisor_state_path is None:
            return
        from ..resilience.supervisor import peek_supervisor_state

        state = peek_supervisor_state(self.supervisor_state_path)
        if state is None:
            return
        self.m_sup_restarts.set(float(state.get("restarts_used", 0)))
        self.m_sup_attempts.set(float(state.get("attempts", 0)))
        outcomes = state.get("outcomes", [])
        self.m_sup_preempted.set(float(outcomes.count("preempted")))
        self.m_sup_hang.set(float(outcomes.count("hang")))
        self.m_sup_crash.set(float(outcomes.count("crash")))

    def health_document(self, *, global_step, process_index: int = 0) -> dict:
        """The /healthz JSON body: liveness AND productivity in one probe
        (the serving-fleet router and the supervisor read the same
        document). Goodput ratio and flight-recorder last-event age are
        None when the respective plane is not attached."""
        heartbeat = (
            self.watchdog.heartbeat_age() if self.watchdog is not None
            else None
        )
        doc = {
            "status": "ok",
            "global_step": global_step,
            "process_index": process_index,
            "watchdog_heartbeat_age_s": heartbeat,
            "goodput_ratio": None,
            "last_event_age_s": None,
        }
        if self.goodput is not None:
            doc["goodput_ratio"] = self.goodput.summary()["goodput_ratio"]
        if self.flightrec is not None:
            doc["last_event_age_s"] = self.flightrec.last_event_age()
        return doc

    # -- bench surface ----------------------------------------------------------

    def breakdown_summary(self) -> dict:
        """Step-time breakdown percentiles + anomaly count for the bench
        JSON line (seconds)."""
        def q(hist, p):
            value = hist.quantile(p)
            return round(value, 6) if value is not None else None

        return {
            "step_p50_s": q(self.m_step, 0.5),
            "step_p95_s": q(self.m_step, 0.95),
            "data_wait_p50_s": q(self.m_data_wait, 0.5),
            "data_wait_p95_s": q(self.m_data_wait, 0.95),
            "host_p50_s": q(self.m_host, 0.5),
            "host_p95_s": q(self.m_host, 0.95),
            "device_p50_s": q(self.m_device, 0.5),
            "device_p95_s": q(self.m_device, 0.95),
            "slow_step_anomalies": self.detector.anomalies,
        }
