from .optim import build_optimizer, adamod, linear_warmup_schedule
from .trainer import Trainer
from .callbacks import TestCallback, AccuracyCallback, MAPCallback, SaveBestCallback
from .checkpoint import save_checkpoint, load_checkpoint

__all__ = [
    "build_optimizer",
    "adamod",
    "linear_warmup_schedule",
    "Trainer",
    "TestCallback",
    "AccuracyCallback",
    "MAPCallback",
    "SaveBestCallback",
    "save_checkpoint",
    "load_checkpoint",
]
