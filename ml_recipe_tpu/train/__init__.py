from .optim import build_optimizer, adamod, linear_warmup_schedule
from .trainer import Trainer
from .callback import TestCallback, AccuracyCallback, MAPCallback, SaveBestCallback
from .checkpoint import (
    CheckpointLayoutError,
    TornCheckpointError,
    load_state_dict,
    peek_checkpoint_layout,
    peek_global_step,
    save_state_dict,
)
from .writer import SummaryWriter, init_writer

__all__ = [
    "build_optimizer",
    "adamod",
    "linear_warmup_schedule",
    "Trainer",
    "TestCallback",
    "AccuracyCallback",
    "MAPCallback",
    "SaveBestCallback",
    "save_state_dict",
    "load_state_dict",
    "peek_global_step",
    "peek_checkpoint_layout",
    "TornCheckpointError",
    "CheckpointLayoutError",
    "SummaryWriter",
    "init_writer",
]
