"""Minimal TensorBoard-compatible scalar writer — pure Python, no TF/torch.

Parity target: reference trainer.py:183-192,215-219 (rank-0-only
``SummaryWriter`` whose dir is wiped per experiment, ``add_scalar`` per loss
head and LR each optimizer step).

Writes standard TFRecord event files (``events.out.tfevents.*``) readable by
TensorBoard: each record is
``[len u64][masked_crc32c(len) u32][payload][masked_crc32c(payload) u32]``
and the payload is a hand-encoded ``tensorflow.Event`` protobuf
(wall_time=1:double, step=2:int64, summary=5 with repeated Value{tag=1,
simple_value=2}). Hand-encoding avoids a protobuf dependency for the three
fields we need.
"""

from __future__ import annotations

import os
import shutil
import socket
import struct
import time
from typing import Optional

_CRC_TABLE = None


def _crc32c_table():
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            table.append(crc)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc32c_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


def _varint(value: int) -> bytes:
    out = bytearray()
    value &= (1 << 64) - 1
    while True:
        byte = value & 0x7F
        value >>= 7
        if value:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _encode_event(wall_time: float, step: int, scalars: Optional[dict] = None,
                  file_version: Optional[str] = None) -> bytes:
    event = bytearray()
    event += _tag(1, 1) + struct.pack("<d", wall_time)  # wall_time: double
    if step:
        event += _tag(2, 0) + _varint(step)  # step: int64
    if file_version is not None:
        fv = file_version.encode()
        event += _tag(3, 2) + _varint(len(fv)) + fv
    if scalars:
        summary = bytearray()
        for name, value in scalars.items():
            tag_bytes = name.encode()
            val = bytearray()
            val += _tag(1, 2) + _varint(len(tag_bytes)) + tag_bytes  # Value.tag
            val += _tag(2, 5) + struct.pack("<f", float(value))  # simple_value
            summary += _tag(1, 2) + _varint(len(val)) + bytes(val)  # Summary.value
        event += _tag(5, 2) + _varint(len(summary)) + bytes(summary)  # Event.summary
    return bytes(event)


def _record(payload: bytes) -> bytes:
    header = struct.pack("<Q", len(payload))
    return (
        header
        + struct.pack("<I", _masked_crc(header))
        + payload
        + struct.pack("<I", _masked_crc(payload))
    )


class SummaryWriter:
    """Append-only scalar event writer; API subset of torch's SummaryWriter."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        fname = (
            f"events.out.tfevents.{int(time.time())}.{socket.gethostname()}.{os.getpid()}"
        )
        self._path = os.path.join(log_dir, fname)
        self._fh = open(self._path, "ab")
        self._fh.write(_record(_encode_event(time.time(), 0, file_version="brain.Event:2")))
        self._fh.flush()
        self._pending = 0

    def add_scalar(self, tag: str, value, global_step: int = 0) -> None:
        payload = _encode_event(time.time(), int(global_step), {tag: float(value)})
        self._fh.write(_record(payload))
        self._pending += 1
        if self._pending >= 512:  # bound event loss under SIGKILL/preemption
            self._fh.flush()
            self._pending = 0

    def flush(self) -> None:
        self._fh.flush()

    def close(self) -> None:
        if not self._fh.closed:
            self._fh.flush()
            self._fh.close()


def init_writer(process_is_primary: bool, writer_dir) -> Optional[SummaryWriter]:
    """Primary-process-only writer whose dir is recreated per experiment
    (reference trainer.py:183-192 semantics, including the wipe warning)."""
    if writer_dir is None or not process_is_primary:
        return None
    import logging

    logging.getLogger(__name__).warning(
        f"Directory {writer_dir} will be cleaned before SummaryWriter "
        f"initialization. To prevent losing important information, use "
        f"different experiment names."
    )
    shutil.rmtree(writer_dir, ignore_errors=True)
    return SummaryWriter(log_dir=str(writer_dir))
