"""Test-time callbacks.

Parity target: reference ``trainer/callback.py:12-108`` — a ``TestCallback``
base with ``at_iteration_end``/``at_epoch_end`` hooks, accuracy and mAP
aggregation, and best-checkpoint saving.

Deltas:
- predictions arrive as host numpy dicts (the trainer gathers device output
  once per eval step);
- ``SaveBestCallback`` compares with a real comparison instead of the
  reference's ``eval(f'{a}{order}{b}')`` string hack (callback.py:98).
"""

from __future__ import annotations

import logging
import math

import numpy as np

from ..metrics import AverageMeter, MAPMeter, accuracy_score

logger = logging.getLogger(__name__)


def _softmax(x: np.ndarray, axis: int = -1) -> np.ndarray:
    x = x - x.max(axis=axis, keepdims=True)
    e = np.exp(x)
    return e / e.sum(axis=axis, keepdims=True)


class TestCallback:
    """Hook base (reference callback.py:12-27)."""

    def at_iteration_end(self, preds, labels, avg_meters):
        self._at_iteration_end(preds, labels, avg_meters)

    def _at_iteration_end(self, *args):
        raise NotImplementedError

    def at_epoch_end(self, avg_meters, trainer):
        self._at_epoch_end(avg_meters, trainer)
        self._reset()

    def _at_epoch_end(self, *args):
        raise NotImplementedError

    def _reset(self):
        pass


class AccuracyCallback(TestCallback):
    """Start/end/cls accuracy with -1 masking (reference callback.py:30-53)."""

    keys = ["start_class", "end_class", "cls"]

    def _at_iteration_end(self, preds, labels, avg_meters):
        start_logits, end_logits, cls_logits = (np.asarray(preds[k]) for k in self.keys)
        start_true, end_true, cls_true = (np.asarray(labels[k]) for k in self.keys)

        start_pred = start_logits.argmax(axis=-1)
        end_pred = end_logits.argmax(axis=-1)
        cls_pred = cls_logits.argmax(axis=-1)

        start_idxs = start_true != -1
        end_idxs = end_true != -1

        # weight each batch-mean by its row count: eval batches are NOT
        # equal-sized (bucketed batches vary by bucket, the trimmed final
        # batch is short), and an unweighted mean-of-means would bias the
        # epoch accuracy toward whichever bucket had more batches
        if start_idxs.any():
            avg_meters["s_acc"].update(
                accuracy_score(start_true[start_idxs], start_pred[start_idxs]),
                int(start_idxs.sum()),
            )
        if end_idxs.any():
            avg_meters["e_acc"].update(
                accuracy_score(end_true[end_idxs], end_pred[end_idxs]),
                int(end_idxs.sum()),
            )
        avg_meters["c_acc"].update(
            accuracy_score(cls_true, cls_pred), int(cls_true.shape[0])
        )

    def _at_epoch_end(self, *args):
        pass


class MAPCallback(TestCallback):
    """Per-class AP -> mAP over cls logits (reference callback.py:56-76)."""

    key = "cls"

    def __init__(self, metric_keys):
        self._metric_keys = list(metric_keys)
        self._reset()

    def _at_iteration_end(self, preds, labels, *args):
        cls_logits = np.asarray(preds[self.key])
        cls_true = np.asarray(labels[self.key])
        self.map_meter.update(
            keys=self._metric_keys,
            pred_probas=_softmax(cls_logits, axis=-1),
            true_labels=cls_true,
        )

    def _at_epoch_end(self, avg_meters, *args):
        avg_meters.update(self.map_meter())

    def _reset(self):
        self.map_meter = MAPMeter()


class SaveBestCallback(TestCallback):
    """Metric-compare-and-save ``best.ch`` (reference callback.py:79-108)."""

    def __init__(self, params):
        self.params = params
        self.metric = params.best_metric
        self.best_order = params.best_order
        self.value = 1e10 * (-1 if self.best_order == ">" else 1)

    def _at_iteration_end(self, *args):
        pass

    def _at_epoch_end(self, avg_meters, trainer):
        metrics = {
            k: v() if isinstance(v, AverageMeter) else v for k, v in avg_meters.items()
        }

        if self.metric not in metrics:
            logger.warning(f"Trainer metrics do not contain metric {self.metric}.")
            return
        value = metrics[self.metric]
        if isinstance(value, float) and math.isnan(value):
            return

        better = value > self.value if self.best_order == ">" else value < self.value
        if better:
            self.value = value
            trainer.save_state_dict(
                self.params.dump_dir / self.params.experiment_name / "best.ch"
            )
            logger.info(
                f"New best {self.metric}={self.value:.3f} at global step "
                f"{trainer.global_step}; wrote best.ch"
            )
        else:
            logger.info(
                f"{self.metric}={value:.3f} did not beat the current best "
                f"{self.value:.3f}; best.ch unchanged"
            )
