"""Composition root.

Parity target: reference ``modules/init.py`` — loss zoo selection
(``init_loss`` init.py:18-40), model+tokenizer construction with fast-native
vs HF fallback (``init_model`` init.py:51-82), dataset construction with
label/sampler weight computation (``init_datasets`` init.py:148-201), collate
binding (``init_collate_fun`` init.py:204-205).

Optimizer construction (reference ``init_optimizer`` init.py:134-145 +
``_get_optimized_parameters`` init.py:85-131) lives in
:func:`ml_recipe_tpu.train.optim.build_optimizer`, invoked inside the Trainer
— on TPU the optimizer is part of the jitted step, so it must be built where
the step is compiled (it needs ``num_training_steps`` for the schedule).
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import os
from typing import Optional, Tuple

import jax
import numpy as np

from .data import (
    ChunkDataset,
    DummyDataset,
    RawPreprocessor,
    SplitDataset,
    collate_fun,
)
from .losses import WeightedLoss, build_loss
from .models import QAModel, resolve_model_config
from .models.hf_convert import load_pretrained_into
from .tokenizer import Tokenizer

logger = logging.getLogger(__name__)


def init_loss(params, train_weights=None) -> WeightedLoss:
    """Loss zoo selection + per-head weights (init.py:18-40)."""
    loss = build_loss(params, train_weights)
    logger.info(f"Used loss function for classification: {params.loss}.")
    return loss


def init_tokenizer(model_params, *, bpe_dropout: Optional[float] = None):
    """First-party fast tokenizer when a vocab file is given; HF fallback
    otherwise (init.py:57-77 semantics, minus the Rust dependency)."""
    model_name = model_params.model.split("-")[0]

    if model_params.vocab_file is not None and not os.path.exists(model_params.vocab_file):
        raise FileNotFoundError(
            f"vocab_file {model_params.vocab_file!r} does not exist. Generate one "
            f"(ml_recipe_tpu.tokenizer.write_synthetic_bert_vocab) or fix the path."
        )

    if model_params.vocab_file is not None:
        return Tokenizer(
            model_name=model_name,
            vocab_file=model_params.vocab_file,
            merges_file=model_params.merges_file,
            lowercase=model_params.lowercase,
            handle_chinese_chars=model_params.handle_chinese_chars,
            dropout=bpe_dropout,
        )

    logger.warning("No vocab file given; falling back to the slower tokenizer path.")
    try:
        if model_name == "bert":
            from transformers import BertTokenizer

            tokenizer = BertTokenizer.from_pretrained(model_params.model)
        elif model_name == "roberta":
            from transformers import RobertaTokenizer

            tokenizer = RobertaTokenizer.from_pretrained(model_params.model)
        else:
            raise NotImplementedError(model_name)
    except Exception as e:  # offline environments have no HF hub access
        raise RuntimeError(
            f"No vocab_file given and HF tokenizer for {model_params.model!r} "
            f"unavailable ({e}). Pass --vocab_file."
        ) from e

    tokenizer.model_name = model_name
    return tokenizer


def init_model(
    model_params,
    *,
    checkpoint: Optional[str] = None,
    bpe_dropout: Optional[float] = None,
    rng_seed: int = 0,
    mesh=None,
    quantize: str = "off",
) -> Tuple[QAModel, dict, object]:
    """Build (model, params, tokenizer) — reference init.py:51-82.

    Weight priority: explicit ``checkpoint`` (our msgpack format, model part
    only — the reference's strict=False torch.load, init.py:43-48) >
    ``model_params.hf_checkpoint`` (converted HF torch weights) > random init.

    ``quantize='int8'`` (serving/eval only): AFTER the float checkpoint is
    restored, the (model, params) pair is converted through
    ``quant.quantize_model`` — post-training per-channel int8, no
    retraining, any existing checkpoint — and the per-layer error summary
    is logged. The checkpoint format itself never changes.
    """
    import jax.numpy as jnp

    tokenizer = init_tokenizer(model_params, bpe_dropout=bpe_dropout)

    cfg = resolve_model_config(model_params, num_labels=len(RawPreprocessor.labels2id))
    dtype = jnp.bfloat16 if getattr(model_params, "compute_dtype", "bfloat16") == "bfloat16" else jnp.float32
    attention_impl = getattr(model_params, "flash_attention", "auto") or "auto"
    if attention_impl == "auto" and mesh is not None:
        from .parallel.sharding import SEQ_AXIS

        if SEQ_AXIS in mesh.axis_names and mesh.shape[SEQ_AXIS] > 1:
            # a seq axis in the mesh IS the long-context request: route
            # attention through the ring dispatcher, which consumes each
            # visiting K/V shard via the composed streaming inner when a
            # legal geometry exists at the local length
            attention_impl = "ring"
            logger.info(
                "Mesh has seq:%d — attention_impl auto-selected 'ring' "
                "(composed streaming-ring for long documents).",
                mesh.shape[SEQ_AXIS],
            )
    model = QAModel(
        cfg,
        dtype=dtype,
        attention_impl=attention_impl,
        remat=getattr(model_params, "remat", False),
        mesh=mesh,  # required by attention_impl='ring' (sequence parallelism)
        ln_impl=getattr(model_params, "ln_impl", "xla") or "xla",
    )

    example = np.zeros((1, 8), dtype=np.int32)
    # Init through an XLA-attention twin: param structure is identical across
    # attention impls, and ring's shard_map would reject the tiny example
    # shape (batch/seq not divisible by the mesh axes).
    init_module = (
        dataclasses.replace(model, attention_impl="xla", mesh=None)
        if model.attention_impl == "ring"
        else model
    )
    params = init_module.init(jax.random.key(rng_seed), example)["params"]

    hf_checkpoint = getattr(model_params, "hf_checkpoint", None)
    if hf_checkpoint:
        params = load_pretrained_into(params, hf_checkpoint, cfg.num_layers)
        logger.info(f"Encoder weights converted from HF checkpoint {hf_checkpoint}.")

    if checkpoint is not None:
        from .train.checkpoint import load_state_dict

        params, _, _, loaded_step = load_state_dict(checkpoint, params=params)
        if loaded_step is not None:
            logger.info(f"Model checkpoint was restored from {checkpoint}.")

    if quantize not in (None, "off"):
        from .quant import quantize_model

        model, params, report = quantize_model(model, params, quantize)
        logger.info(
            "Post-training quantization (%s): %d kernels converted, "
            "params %.1f -> %.1f MB (kernels %.1f -> %.1f MB), worst "
            "per-layer relative RMS error %.4f.",
            quantize, report["n_quantized"],
            report["orig_bytes"] / 1e6, report["quant_bytes"] / 1e6,
            report["orig_kernel_bytes"] / 1e6,
            report["quant_kernel_bytes"] / 1e6,
            report["max_rel_rms_err"],
        )

    return model, params, tokenizer


def init_datasets(params, *, tokenizer=None, clear: bool = False, rng=None):
    """Datasets + label/sampler weights (init.py:148-201).

    TPU delta: the test dataset is built on EVERY process (eval runs SPMD;
    the reference gated it to rank 0, init.py:195-200).
    """
    weights = {"label_weights": None, "sampler_weights": None}

    if getattr(params, "dummy_dataset", False):
        logger.warning("Dummy dataset is used to train model.")
        common = dict(
            data_dir=None,
            tokenizer=tokenizer,
            indexes=None,
            max_seq_len=params.max_seq_len,
            max_question_len=params.max_question_len,
            rng=rng,
        )
        return DummyDataset(**common), DummyDataset(dataset_len=1024, **common), weights

    preprocessor = RawPreprocessor(
        raw_json=params.data_path, out_dir=params.processed_data_path, clear=clear
    )
    labels_counter, labels, (train_indexes, train_labels, test_indexes, test_labels) = (
        preprocessor()
    )

    if getattr(params, "train_label_weights", False):
        label_weights = np.asarray(
            [1 / labels_counter[k] for k in sorted(labels_counter.keys())]
        )
        label_weights = label_weights / np.sum(label_weights)
        logger.info(
            "Label weights: "
            + ", ".join(
                f"{RawPreprocessor.id2labels[k]} ({k}) - {v:.4f}"
                for k, v in enumerate(label_weights)
            )
            + "."
        )
        weights["label_weights"] = label_weights

    if getattr(params, "train_sampler_weights", False):
        sampler_weights = np.asarray([1 / labels_counter[label] for label in train_labels])
        weights["sampler_weights"] = sampler_weights / np.sum(sampler_weights)

    common = dict(
        tokenizer=tokenizer,
        max_seq_len=params.max_seq_len,
        max_question_len=params.max_question_len,
        doc_stride=params.doc_stride,
        split_by_sentence=params.split_by_sentence,
        truncate=params.truncate,
        rng=rng,
    )
    train_dataset = SplitDataset(params.processed_data_path, indexes=train_indexes, **common)
    test_dataset = SplitDataset(
        params.processed_data_path, indexes=test_indexes, test=True, **common
    )

    return train_dataset, test_dataset, weights


def init_validation_dataset(params, *, tokenizer=None, clear: bool = False, rng=None):
    """Held-out split as a ChunkDataset (reference validate.py:15-26)."""
    preprocessor = RawPreprocessor(
        raw_json=params.data_path, out_dir=params.processed_data_path, clear=clear
    )
    _, _, (_, _, val_indexes, val_labels) = preprocessor()

    return ChunkDataset(
        params.processed_data_path,
        tokenizer,
        val_indexes,
        test=False,
        split_by_sentence=True,
        truncate=True,
        rng=rng,
    )


def init_collate_fun(tokenizer, *, max_seq_len: Optional[int] = None, return_items: bool = False):
    """Bind tokenizer + static shape (init.py:204-205; fixed-shape TPU delta)."""
    return functools.partial(
        collate_fun, tokenizer=tokenizer, max_seq_len=max_seq_len, return_items=return_items
    )
