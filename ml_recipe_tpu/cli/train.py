"""Distributed training entry point.

Parity target: reference ``modules/train.py`` — config parsing + round-trip
serialization (train.py:151-165), topology setup, worker bootstrap with NCCL
rendezvous (train.py:18-59), Trainer construction with after-epoch hooks
``save_last``/``save_each``/``test_fun`` (train.py:104-116), KeyboardInterrupt
-> ``interrupt.ch`` (train.py:117-119).

TPU redesign: ONE process per host (no ``mp.spawn`` fan-out — SPMD covers all
local devices through the mesh), ``jax.distributed.initialize`` replaces the
TCP process group, and the mesh spec replaces world-size arithmetic
(train.py:133-136). Run under the same env contract the platform launcher
exports (MASTER_IP/MASTER_PORT/LOCAL_RANK/WORLD_SIZE → flags, worker.sh:6).

Usage::

    python -m ml_recipe_tpu.cli.train -c config/test_bert.cfg [--flag value ...]
"""

from __future__ import annotations

import functools
import logging
import os
import signal
import sys
import threading
from datetime import datetime

from ..compose import init_collate_fun, init_datasets, init_loss, init_model
from ..config.parser import (
    get_model_parser,
    get_params,
    get_trainer_parser,
    write_config_file,
)
from ..data import RawPreprocessor
from ..data.bucketing import parse_length_buckets
from ..parallel import ParallelPlan, barrier, initialize_from_params, is_primary
from ..train import AccuracyCallback, MAPCallback, SaveBestCallback, Trainer
from ..utils.logging import get_logger, show_params
from ..utils.seed import set_seed

logger = logging.getLogger(__name__)


def _arm_watchdog(params):
    """Install the process-global step watchdog from ``--watchdog_timeout``
    (or CLEAR it when unset — a stale instance from a previous in-process
    run must not keep governing barrier call sites). Must run BEFORE the
    distributed rendezvous: a rendezvous that never completes is the
    canonical startup hang the watchdog exists to catch."""
    from ..resilience import watchdog as watchdog_mod

    timeout = getattr(params, "watchdog_timeout", None)
    return watchdog_mod.install(
        watchdog_mod.Watchdog(timeout) if timeout else None
    )


def run_worker(params, model_params) -> None:
    """One SPMD host process (reference run_worker, train.py:18-122)."""
    from ..resilience import watchdog as watchdog_mod

    # Step watchdog: armed around every train/eval step and checkpoint
    # barrier; a missed deadline dumps stacks and aborts with a distinct
    # exit code so a supervisor restarts instead of the pod wedging.
    # main() normally armed it before the rendezvous; arm here only for
    # direct run_worker callers (embedding launchers) — and tear it down
    # symmetrically, so a second config in the same process neither
    # inherits a stale instance nor leaks monitor threads.
    watchdog = watchdog_mod.current()
    locally_armed = False
    if watchdog is None and getattr(params, "watchdog_timeout", None):
        watchdog = _arm_watchdog(params)
        locally_armed = True
    try:
        _run_worker(params, model_params, watchdog)
    finally:
        if locally_armed:
            watchdog.stop()
            watchdog_mod.install(None)


def _run_worker(params, model_params, watchdog) -> None:
    import jax

    log_file = params.log_file if is_primary() else None
    log_level = logging.INFO if is_primary() else logging.WARN
    local_logger = get_logger(
        level=log_level, filename=str(log_file) if log_file else None,
        filemode="a", logger_name="train", debug=params.debug,
    )

    # Geometry autotuner wiring: --autotune / --autotune_cache drive the
    # process-wide selector the attention kernels consult (ops/autotune.py).
    from ..ops import aot, autotune

    autotune.configure(
        enabled=getattr(params, "autotune", True),
        cache_dir=getattr(params, "autotune_cache", None),
    )
    # AOT program-store wiring: --aot_cache is 'off' | a directory | None
    # (default directory). A warm restart deserializes its train-step
    # programs from the store instead of recompiling them (ops/aot.py).
    _aot_cache = getattr(params, "aot_cache", None)
    aot.configure(
        enabled=_aot_cache != "off",
        cache_dir=_aot_cache if _aot_cache not in (None, "off") else None,
        cache_bytes=getattr(params, "aot_cache_bytes", 0) or None,
    )

    # the declarative parallelism plan: built ONCE from --mesh; the
    # trainer (and through it the ZeRO-1 planner, HBM pre-flight and
    # checkpoint manifests) derives every sharding from it. With
    # --elastic on the requested mesh may no longer fit the live device
    # set (a restart after host loss): the data axis shrinks, structural
    # axes refuse (parallel/mesh.elastic_axes).
    if getattr(params, "elastic", "off") != "off":
        plan = ParallelPlan.elastic_from_spec(params.mesh)
        if plan.shrunk:
            local_logger.warning(
                f"ELASTIC RESUME: mesh re-derived for the live device set: "
                f"requested {plan.requested_axes} -> running {plan.describe()}."
            )
    else:
        plan = ParallelPlan.from_spec(params.mesh)
    mesh = plan.mesh
    local_logger.warning(
        f"Process {jax.process_index()}/{jax.process_count()}. "
        f"Mesh: {plan.describe()} "
        f"({plan.unused_devices} visible device(s) unused). "
        f"Global batch {params.train_batch_size} spans the whole data axis — "
        f"scale the learning rate for the GLOBAL batch, not per-device."
    )

    rng_pool = set_seed(params.seed)
    data_rng = rng_pool.host_rng("chunk_sampling") if rng_pool else None

    # Observability plane (all off by default): --trace_spans installs the
    # process-global span tracer (trainer + checkpoint call sites emit
    # through it), --metrics_port builds the training telemetry registry
    # whose exporter starts once the Trainer exists (its health document
    # reads live trainer state). Tracer install and the teardown of both
    # bracket EVERYTHING below — a startup failure (model init, dataset
    # build, a corrupt --last restore) must uninstall the process-global
    # tracer and close the exporter port, not leak the instrumented path
    # into later in-process runs.
    tracer = None
    if getattr(params, "trace_spans", None):
        from ..metrics import trace as trace_mod

        tracer = trace_mod.install(trace_mod.TraceWriter(
            os.path.join(
                str(params.trace_spans),
                f"train_trace_p{jax.process_index()}.json",
            ),
            process_name="train",
        ))

    state = {"exporter": None}
    try:
        _run_instrumented(
            params, model_params, watchdog, local_logger, plan, data_rng,
            state,
        )
    finally:
        if state["exporter"] is not None:
            state["exporter"].close()
        if tracer is not None:
            from ..metrics import trace as trace_mod

            trace_mod.install(None)
            tracer.close()  # flush the span file even on a non-clean exit


def _run_instrumented(params, model_params, watchdog, local_logger, plan,
                      data_rng, state) -> None:
    import jax

    from ..ops import aot

    mesh = plan.mesh
    exp_dir = params.dump_dir / params.experiment_name

    if (
        getattr(params, "elastic", "off") != "off"
        and os.environ.get("MLRT_SUPERVISED")
        and watchdog is not None
    ):
        # elastic child heartbeat: piggyback on the step watchdog's beat so
        # the cross-host coordination file carries this child's last
        # completed step at training cadence (peer supervisors read it as
        # the straggler/liveness signal) — no second timer thread
        from ..resilience.coordination import COORD_DIRNAME, write_child_heartbeat
        from ..resilience.faults import current_host

        _coord_dir = os.path.join(str(exp_dir), COORD_DIRNAME)
        _host = current_host()
        watchdog.add_on_beat(
            lambda step: write_child_heartbeat(_coord_dir, _host, step=step)
        )
    telemetry = None
    goodput = None
    flightrec = None
    if getattr(params, "goodput_ledger", False):
        from ..metrics.goodput import GOODPUT_FILENAME, GoodputLedger

        # lives next to supervisor_state.json; construction reads prior
        # attempts' events, so a resumed run reports whole-run goodput.
        # Only process 0 writes the shared file: every host feeds the same
        # global steps, so N file-backed writers would multiply productive
        # time by N in the run summary — peers keep an in-memory ledger
        # (their local /metrics gauges stay honest) and process 0's file
        # is the run-level record
        goodput = GoodputLedger(
            os.path.join(str(exp_dir), GOODPUT_FILENAME)
            if jax.process_index() == 0 else None,
            process_index=jax.process_index(),
        )
    if getattr(params, "flight_recorder", False):
        from ..metrics.flightrec import FlightRecorder

        flightrec = FlightRecorder.open_in(
            str(exp_dir), process_index=jax.process_index(),
            capacity=getattr(params, "flightrec_events", 256),
        )
        if plan.shrunk:
            # the crash-loop diagnosis timeline must explain a topology
            # change: this attempt runs NARROWER than the operator asked
            flightrec.record(
                "mesh_shrunk", old=plan.requested_axes, new=plan.describe(),
            )
        if watchdog is not None:
            # a hang abort dumps the last-K-step timeline before the
            # watchdog's os._exit(87)
            watchdog.add_on_timeout(
                lambda label: flightrec.dump("watchdog", label=label)
            )
    if (
        getattr(params, "metrics_port", None) is not None
        or goodput is not None
        or flightrec is not None
    ):
        from ..resilience.supervisor import STATE_FILENAME
        from ..train.telemetry import TrainTelemetry

        # the telemetry plane is also how the ledger/recorder get their
        # per-step feeds, so either flag builds it; the HTTP exporter
        # itself still starts only with --metrics_port
        telemetry = TrainTelemetry(
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            anomaly_factor=getattr(params, "anomaly_factor", 3.0),
            anomaly_window=getattr(params, "anomaly_window", 64),
            watchdog=watchdog,
            # the supervisor (parent process) keeps this sidecar current;
            # reading it cross-process is what puts restart counts on the
            # child's /metrics without any coordination channel
            supervisor_state_path=os.path.join(str(exp_dir), STATE_FILENAME),
            goodput=goodput,
            flightrec=flightrec,
        )

    model, model_state, tokenizer = init_model(
        model_params, bpe_dropout=params.bpe_dropout,
        rng_seed=params.seed if params.seed is not None else 0,
        mesh=mesh,
    )

    # Rank 0 prepares the (shared-dir) dataset; everyone else waits, then
    # loads the cached artifacts (train.py:49-59).
    if is_primary():
        train_dataset, test_dataset, train_weights = init_datasets(
            params, tokenizer=tokenizer, clear=params.clear_processed, rng=data_rng
        )
    barrier("dataset_prep")
    if not is_primary():
        train_dataset, test_dataset, train_weights = init_datasets(
            params, tokenizer=tokenizer, clear=False, rng=data_rng
        )

    loss = init_loss(params, train_weights)

    trainer = Trainer(
        model=model,
        params=model_state,
        loss=loss,
        collate_fun=init_collate_fun(tokenizer, max_seq_len=params.max_seq_len),
        trainer_params=params,
        train_dataset=train_dataset,
        test_dataset=test_dataset,
        writer_dir=params.dump_dir / f"board/{params.experiment_name}",
        mesh=mesh,
        n_epochs=params.n_epochs,
        train_batch_size=params.train_batch_size,
        test_batch_size=params.test_batch_size,
        batch_split=params.batch_split,
        n_jobs=params.n_jobs,
        warmup_coef=params.warmup_coef,
        max_grad_norm=params.max_grad_norm,
        train_weights=train_weights,
        drop_optimizer=params.drop_optimizer,
        debug=params.debug,
        seed=params.seed if params.seed is not None else 0,
        optimizer_sharding=getattr(params, "optimizer_sharding", None),
        shard_optimizer=getattr(params, "shard_optimizer", False),
        pipe_schedule=getattr(params, "pipe_schedule", "gpipe"),
        pipe_param_sharding=getattr(params, "pipe_param_sharding", "auto"),
        zero1_overlap=getattr(params, "zero1_overlap", "off"),
        zero1_bucket_mb=getattr(params, "zero1_bucket_mb", 4.0),
        async_checkpoint=getattr(params, "async_checkpoint", False),
        sharded_checkpoint=getattr(params, "sharded_checkpoint", False),
        trace_dir=(
            params.dump_dir / f"board/{params.experiment_name}/trace"
            if getattr(params, "trace", False) else None
        ),
        watchdog=watchdog,
        hbm_preflight=getattr(params, "hbm_preflight", True),
        length_buckets=parse_length_buckets(
            getattr(params, "length_buckets", None), params.max_seq_len
        ),
        sequence_packing=getattr(params, "sequence_packing", False),
        pack_max_segments=getattr(params, "pack_max_segments", 8),
        pack_splitting=getattr(params, "pack_splitting", "off"),
        pack_min_fragment=getattr(params, "pack_min_fragment", 32),
        device_prefetch=getattr(params, "device_prefetch", 0),
        log_every=getattr(params, "log_every", 10),
        telemetry=telemetry,
    )

    if params.last is not None:
        trainer.load_state_dict(params.last)

    if goodput is not None:
        # the FIRST step id this attempt will execute: the summarizer
        # reclassifies previously ledgered work on steps >= it as the
        # recompute badput a resume pays
        goodput.note_run_start(trainer.global_step)
    if flightrec is not None:
        flightrec.record("run_start", step=trainer.global_step)

    if telemetry is not None and getattr(params, "metrics_port", None) is not None:
        from ..metrics.exporter import MetricsExporter

        # multi-host: each process exports its own plane one port up from
        # the base (port 0 = ephemeral stays ephemeral everywhere)
        base_port = int(params.metrics_port)
        port = base_port + jax.process_index() if base_port else 0

        def health():
            # one liveness+productivity probe: goodput ratio and flight-
            # recorder last-event age ride the same document the serving
            # fleet's router and the supervisor poll
            return telemetry.health_document(
                global_step=trainer.global_step,
                process_index=jax.process_index(),
            )

        # the caller's finally closes it, whatever unwinds from here on
        state["exporter"] = MetricsExporter(
            telemetry.registry, port=port, health_fn=health,
        ).start()
        state["exporter"].add_pre_render(telemetry.refresh)

        hosts = getattr(params, "metrics_hosts", None)
        if hosts and jax.process_index() == 0:
            from ..metrics.aggregator import PodAggregator

            # process 0 fans in every host's exporter into one merged
            # pod page (sum/min/max, per-host views, straggler gauges)
            aggregator = PodAggregator(str(hosts).split(","))
            state["exporter"].add_route("/metrics/pod", aggregator.render)
            local_logger.info(
                f"Pod-scope aggregation over {len(aggregator.targets)} "
                f"host exporter(s) at /metrics/pod."
            )

    def save_last(*args, **kwargs):
        trainer.save_state_dict(params.dump_dir / params.experiment_name / "last.ch")

    def save_each(epoch_i):
        trainer.save_state_dict(
            params.dump_dir / params.experiment_name / f"epoch_{epoch_i}.ch"
        )

    test_fun = functools.partial(
        trainer.test,
        callbacks=[
            MAPCallback(list(RawPreprocessor.labels2id.keys())),
            AccuracyCallback(),
            SaveBestCallback(params),
        ],
    )

    # TPU preemptions/evictions deliver SIGTERM (not SIGINT): route it into
    # the same interrupt-checkpoint path as Ctrl-C (reference train.py:117-119
    # only covered KeyboardInterrupt). Installed here — after Trainer
    # construction — so a SIGTERM during compile/init still aborts cleanly.
    def _sigterm_to_interrupt(signum, frame):
        raise KeyboardInterrupt(f"signal {signum}")

    # signal.signal raises ValueError off the main thread — an embedding
    # launcher running run_worker from a worker thread should train without
    # the SIGTERM hook, not crash before the first step.
    on_main_thread = threading.current_thread() is threading.main_thread()
    if on_main_thread:
        prev_handler = signal.signal(signal.SIGTERM, _sigterm_to_interrupt)
    else:
        local_logger.info(
            "Not on the main thread; SIGTERM-to-checkpoint handler not installed."
        )
    try:
        trainer.train(after_epoch_funcs=[save_last, save_each, test_fun])
    except KeyboardInterrupt:
        # disarm first: a second SIGTERM during the (multi-second) save must
        # not re-raise inside save_state_dict and abort the very checkpoint
        # this path exists to produce
        if on_main_thread:
            signal.signal(signal.SIGTERM, signal.SIG_IGN)
        local_logger.error("Training process was interrupted.")
        if flightrec is not None:
            # before the (fallible) interrupt save: the timeline into the
            # preemption must survive even a failed emergency checkpoint
            flightrec.dump("sigterm", step=trainer.global_step)
        if goodput is not None:
            # same ordering: the open step window's accounting must land
            # durably even if the emergency save below fails
            goodput.flush()
        # drain any STALE background-persist failure non-strictly first: a
        # failed earlier save (already logged) must not abort the very
        # emergency checkpoint this path exists to produce
        trainer.finish_pending_checkpoint(raise_errors=False)
        trainer.save_state_dict(params.dump_dir / params.experiment_name / "interrupt.ch")
        # async checkpointing: the interrupt save must be DURABLE before
        # this process exits and the supervisor resumes from it — a resume
        # that races the background persist would restart from stale state
        trainer.finish_pending_checkpoint()
        if goodput is not None:
            _store = aot.get()
            goodput.note_aot(
                _store.hits, _store.misses, sum(_store.load_times_s))
            goodput.note_run_end(trainer.global_step)
            local_logger.warning(goodput.summary_message())
        # under a supervisor, a caught preemption is a reason to RESUME:
        # exit with the tempfail code the supervisor classifies as
        # 'preempted' (a bare return here would read as a clean finish)
        if os.environ.get("MLRT_SUPERVISED"):
            from ..resilience.supervisor import PREEMPT_EXIT_CODE

            raise SystemExit(PREEMPT_EXIT_CODE)
    except Exception as e:
        local_logger.error(e)
        if flightrec is not None:
            flightrec.dump("exception", error=f"{type(e).__name__}: {e}")
        if goodput is not None:
            goodput.flush()  # keep the open step window's accounting
        # best-effort completion barrier: let an in-flight persist land (a
        # valid checkpoint to resume from beats a torn one) but never mask
        # the propagating error with a persist failure
        trainer.finish_pending_checkpoint(raise_errors=False)
        raise e
    else:
        # at-exit completion barrier: a clean run must not report success
        # while its final checkpoint is still (or failed) persisting
        trainer.finish_pending_checkpoint()
        if goodput is not None:
            # this attempt's program-store tally: a zero-compile warm
            # restart is visible in the ledger as an aot event with
            # misses == 0 next to a load-time-only compile_warmup share
            _store = aot.get()
            goodput.note_aot(
                _store.hits, _store.misses, sum(_store.load_times_s))
            goodput.note_run_end(trainer.global_step)
            local_logger.warning(goodput.summary_message())
        if flightrec is not None:
            flightrec.record("run_end", step=trainer.global_step)
            flightrec.dump("clean")
    finally:
        if on_main_thread:
            signal.signal(signal.SIGTERM, prev_handler)


def main(params, model_params) -> None:
    show_params(model_params, "model")
    show_params(params, "trainer")

    # Arm the watchdog BEFORE joining the world: the rendezvous itself is
    # the first thing that can hang (one host missing) and its watch frame
    # only exists if the watchdog is already installed.
    watchdog = _arm_watchdog(params)

    try:
        # Join the multi-host world BEFORE any jax device use (train.py:27-28's
        # init_process_group, re-expressed as jax.distributed.initialize).
        initialize_from_params(params)

        run_worker(params, model_params)
    finally:
        # stop the monitor and clear the global slot so an embedding caller
        # running several configs in one process never inherits a stale one
        if watchdog is not None:
            watchdog.stop()
        from ..resilience import watchdog as watchdog_mod

        watchdog_mod.install(None)


def cli() -> None:
    from ..utils.platform import honor_env_platform

    honor_env_platform()
    (parser, model_parser), (params, model_params) = get_params(
        (get_trainer_parser, get_model_parser)
    )

    os.makedirs(params.dump_dir / params.experiment_name, exist_ok=True)

    # Fault drills: arm the configured plan in THIS process (children of the
    # supervisor re-arm from their own argv/config/env).
    if getattr(params, "fault_plan", None):
        from ..resilience import faults

        faults.install_plan(params.fault_plan)

    # --supervise: this process becomes the supervisor; each attempt is a
    # child running the same CLI minus the flag (MLRT_SUPERVISED breaks the
    # recursion even when `supervise` comes from a config file) with --last
    # re-pointed at the newest valid checkpoint.
    if getattr(params, "supervise", False) and not os.environ.get("MLRT_SUPERVISED"):
        from ..resilience.supervisor import supervise_cli

        raise SystemExit(supervise_cli(params, sys.argv[1:]))

    params.log_file = (
        params.dump_dir / params.experiment_name
        / f'{datetime.now().strftime("%d-%m-%Y_%H-%M-%S")}.log'
        if params.local_rank in [-1, 0]
        else None
    )

    params.n_jobs = max(1, min(params.n_jobs, (os.cpu_count() or 2) // 2))

    get_logger(
        filename=str(params.log_file) if params.log_file else None,
        filemode="w", logger_name="train", debug=params.debug,
    )

    if params.local_rank in [0, -1]:
        write_config_file(parser, params, params.dump_dir / params.experiment_name / "trainer.cfg")
        write_config_file(
            model_parser, model_params, params.dump_dir / params.experiment_name / "model.cfg"
        )

    main(params, model_params)


if __name__ == "__main__":
    cli()
