"""Serving-fleet entry point: router tier + N supervised engines.

Boots the fleet subsystem (``ml_recipe_tpu/fleet/``): launch N
``cli.serve`` engine children against the shared AOT program store (each
warms its bucket grid before admitting traffic), put the consistent-hash
router in front of them, and serve ``POST /v1/qa`` until SIGTERM. The
router sheds load health-first; crashed engines are classified with the
``resilience/`` exit-code contract and relaunched behind the router's
ejection. ``--rolling_restart true`` performs one zero-compile rolling
restart pass once the tier is up.

Usage::

    python -m ml_recipe_tpu.cli.fleet -c config/fleet.cfg

``--host``/``--port`` bind the ROUTER; engines always bind ephemeral
ports on the same host. ``--ready_file`` documents the router address +
every engine endpoint once the whole tier admits traffic.
"""

from __future__ import annotations

import json
import os
import signal
import tempfile
import threading
from pathlib import Path

from ..config.parser import (
    get_fleet_parser,
    get_model_parser,
    get_params,
    get_serve_parser,
)
from ..fleet import FleetManager, FleetRouter
from ..utils.logging import get_logger, show_params

# (flag, attr, kind) map from the parsed serve+model namespaces onto the
# engine-child argv. 'value' flags are skipped when None; 'bool' flags
# are forwarded as true/false (_str2bool surface); 'switch' flags are
# store_true and forwarded only when set.
_MODEL_FLAGS = (
    ("--model", "model", "value"),
    ("--vocab_file", "vocab_file", "value"),
    ("--merges_file", "merges_file", "value"),
    ("--lowercase", "lowercase", "switch"),
    ("--handle_chinese_chars", "handle_chinese_chars", "switch"),
    ("--hf_checkpoint", "hf_checkpoint", "value"),
    ("--param_dtype", "param_dtype", "value"),
    ("--compute_dtype", "compute_dtype", "value"),
    ("--flash_attention", "flash_attention", "value"),
    ("--ln_impl", "ln_impl", "value"),
    ("--max_position_embeddings", "max_position_embeddings", "value"),
)
_SERVE_FLAGS = (
    ("--host", "host", "value"),
    ("--buckets", "buckets", "value"),
    ("--max_batch_delay_ms", "max_batch_delay_ms", "value"),
    ("--queue_size", "queue_size", "value"),
    ("--request_timeout_s", "request_timeout_s", "value"),
    ("--drain_timeout_s", "drain_timeout_s", "value"),
    ("--max_question_len", "max_question_len", "value"),
    ("--doc_stride", "doc_stride", "value"),
    ("--mesh", "mesh", "value"),
    ("--autotune", "autotune", "bool"),
    ("--autotune_cache", "autotune_cache", "value"),
    ("--aot_cache", "aot_cache", "value"),
    ("--aot_cache_bytes", "aot_cache_bytes", "value"),
    ("--hbm_preflight", "hbm_preflight", "bool"),
    ("--serve_cache_bytes", "serve_cache_bytes", "value"),
    ("--doc_cache_bytes", "doc_cache_bytes", "value"),
    ("--quantize", "quantize", "value"),
    ("--trace_spans", "trace_spans", "value"),
)


def engine_argv(serve_params, model_params) -> list:
    """The common ``cli.serve`` child argv from the parsed namespaces
    (everything but --port/--ready_file/--checkpoint, which the manager
    owns per-engine)."""
    argv = []
    for flags, params in ((_MODEL_FLAGS, model_params),
                          (_SERVE_FLAGS, serve_params)):
        for flag, attr, kind in flags:
            value = getattr(params, attr, None)
            if kind == "switch":
                if value:
                    argv.append(flag)
            elif kind == "bool":
                argv.extend([flag, "true" if value else "false"])
            elif value is not None:
                argv.extend([flag, str(value)])
    return argv


def main(fleet_params, params, model_params) -> int:
    show_params(model_params, "model")
    show_params(params, "serve")
    show_params(fleet_params, "fleet")

    run_dir = Path(
        fleet_params.fleet_run_dir
        or tempfile.mkdtemp(prefix="mlrt_fleet_")
    )
    checkpoints = None
    if fleet_params.engine_checkpoints:
        checkpoints = [
            c.strip() or None
            for c in fleet_params.engine_checkpoints.split(",")
        ]
    elif params.checkpoint:
        checkpoints = [params.checkpoint]

    router = FleetRouter(
        host=params.host,
        port=params.port,
        ring_replicas=fleet_params.ring_replicas,
        health_poll_s=fleet_params.health_poll_s,
        eject_after=fleet_params.eject_after,
        degrade_weight=fleet_params.degrade_weight,
        queue_pressure=fleet_params.queue_pressure,
        spill_retries=fleet_params.spill_retries,
        request_timeout_s=params.request_timeout_s,
        routing=fleet_params.routing,
    )
    manager = FleetManager(
        engine_argv(params, model_params),
        n_engines=fleet_params.engines,
        run_dir=run_dir,
        checkpoints=checkpoints,
        drain_timeout_s=params.drain_timeout_s,
        router=router,
    )

    stop = threading.Event()

    def _on_signal(signum, frame):  # noqa: ARG001 - signal API
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    try:
        manager.start()
        router.start()

        if params.ready_file:
            # orchestration hook: the router is listening and every
            # engine's bucket grid is compiled — traffic is safe to send
            ready = Path(params.ready_file)
            tmp = ready.with_name(ready.name + ".tmp")
            tmp.write_text(json.dumps({
                "host": router.host, "port": router.port, "pid": os.getpid(),
                "engines": [
                    {"node": ep.node_id, "host": ep.host, "port": ep.port,
                     "checkpoint": ep.checkpoint}
                    for ep in router.endpoints()
                ],
            }))
            os.replace(tmp, ready)

        if fleet_params.rolling_restart:
            manager.rolling_restart()

        while not stop.wait(2.0):
            manager.reap()
    finally:
        manager.stop()
        router.close()
    return 0


def cli() -> None:
    from ..utils.platform import honor_env_platform

    honor_env_platform()
    _, (fleet_params, params, model_params) = get_params(
        (get_fleet_parser, get_serve_parser, get_model_parser)
    )
    get_logger(logger_name="fleet")

    raise SystemExit(main(fleet_params, params, model_params))


if __name__ == "__main__":
    cli()
