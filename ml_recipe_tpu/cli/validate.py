"""Validation / prediction entry point.

Parity target: reference ``modules/validate.py`` — load checkpoint, build a
``ChunkDataset`` over the held-out split (validate.py:15-26), run the
``Predictor`` over all chunks (validate.py:29-54).

The reference swapped its fast Rust tokenizer for the slow HF one here
because the Rust object could not cross ``mp.Pool`` pickling
(validate.py:37-39 todo). Our first-party tokenizer streams through the
thread-pool ``ListDataloader`` directly — no swap needed.

Usage::

    python -m ml_recipe_tpu.cli.validate -c config/validate.cfg
"""

from __future__ import annotations

import os

from ..compose import init_collate_fun, init_model, init_validation_dataset
from ..config.parser import get_model_parser, get_params, get_predictor_parser
from ..data.bucketing import parse_length_buckets
from ..infer import Predictor
from ..parallel import ParallelPlan
from ..utils.logging import get_logger, show_params


def main(params, model_params):
    show_params(model_params, "model")
    show_params(params, "predictor")

    # --quantize int8: offline eval of the int8 serving path — the same
    # conversion the serving engine performs at startup, so span-level
    # accuracy of a quantized deployment can be measured before it ships
    model, model_state, tokenizer = init_model(
        model_params, checkpoint=params.checkpoint,
        quantize=getattr(params, "quantize", "off"),
    )

    val_dataset = init_validation_dataset(params, tokenizer=tokenizer, clear=False)

    collate_fun = init_collate_fun(
        tokenizer, max_seq_len=params.max_seq_len, return_items=True
    )
    predictor = Predictor(
        model,
        model_state,
        # one declarative plan from --mesh; the predictor derives its
        # batch placement from it
        mesh=ParallelPlan.from_spec(getattr(params, "mesh", None)).mesh,
        collate_fun=collate_fun,
        batch_size=params.batch_size,
        n_jobs=params.n_jobs,
        buffer_size=params.buffer_size,
        limit=params.limit,
        fetch_every=params.fetch_every,
        length_buckets=parse_length_buckets(
            getattr(params, "length_buckets", None), params.max_seq_len
        ),
        sequence_packing=getattr(params, "sequence_packing", False),
        pack_max_segments=getattr(params, "pack_max_segments", 8),
        pack_splitting=getattr(params, "pack_splitting", "off"),
        pack_min_fragment=getattr(params, "pack_min_fragment", 32),
    )

    predictor(val_dataset)

    return predictor


def cli() -> None:
    from ..utils.platform import honor_env_platform

    honor_env_platform()
    _, (params, model_params) = get_params((get_predictor_parser, get_model_parser))
    get_logger(logger_name="validate")

    params.n_jobs = max(1, min(params.n_jobs, (os.cpu_count() or 2) // 2))

    main(params, model_params)


if __name__ == "__main__":
    cli()
