"""Offline metric evaluation entry point.

Parity target: reference ``modules/train_metrics.py`` — re-run the Trainer's
test loop with MAP/Accuracy callbacks on BOTH the train and test splits from
a saved checkpoint (train_metrics.py:13-55).

Usage::

    python -m ml_recipe_tpu.cli.train_metrics -c config/validate.cfg
"""

from __future__ import annotations

import logging
import os

from ..compose import init_collate_fun, init_datasets, init_loss, init_model
from ..config.parser import (
    get_model_parser,
    get_params,
    get_predictor_parser,
    get_trainer_parser,
)
from ..data import RawPreprocessor
from ..parallel import build_mesh
from ..train import AccuracyCallback, MAPCallback, Trainer
from ..utils.logging import get_logger, show_params

logger = logging.getLogger(__name__)


def run_test(params):
    """Test-only Trainer (train_metrics.py:13-34)."""
    trainer = Trainer(
        model=params.model,
        params=params.model_state,
        loss=params.loss,
        collate_fun=params.collate_fun,
        test_dataset=params.dataset,
        mesh=params.mesh_obj,
        test_batch_size=params.batch_size,
        n_jobs=params.n_jobs,
        debug=getattr(params, "debug", False),
    )

    callbacks = [
        MAPCallback(list(RawPreprocessor.labels2id.keys())),
        AccuracyCallback(),
    ]

    return trainer.test(-1, callbacks=callbacks)


def main(params, model_params) -> None:
    show_params(model_params, "model")
    show_params(params, "test")

    params.model, params.model_state, params.tokenizer = init_model(
        model_params, checkpoint=params.checkpoint
    )
    params.mesh_obj = build_mesh(getattr(params, "mesh", None))

    train_dataset, test_dataset, weights = init_datasets(
        params, tokenizer=params.tokenizer, clear=False
    )
    params.loss = init_loss(params, weights)
    params.collate_fun = init_collate_fun(params.tokenizer, max_seq_len=params.max_seq_len)

    logger.info("Train dataset validation..")
    params.dataset = train_dataset
    run_test(params)

    logger.info("Test dataset validation..")
    params.dataset = test_dataset
    run_test(params)


def cli() -> None:
    from ..utils.platform import honor_env_platform

    honor_env_platform()
    # The reference parsed with the predictor parser only (train_metrics.py:59)
    # yet init_loss/init_datasets read trainer-parser flags (loss, w_*,
    # dummy_dataset, ...) — a latent crash. Route all three parsers and fill
    # loss/dataset knobs from the trainer namespace.
    _, (params, trainer_ns, model_params) = get_params(
        (get_predictor_parser, get_trainer_parser, get_model_parser)
    )
    for key, value in vars(trainer_ns).items():
        if not hasattr(params, key):
            setattr(params, key, value)

    params.n_jobs = max(1, min(params.n_jobs, (os.cpu_count() or 2) // 2))

    get_logger(logger_name="train_metrics")

    main(params, model_params)


if __name__ == "__main__":
    cli()
