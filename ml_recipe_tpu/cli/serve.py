"""Online QA serving entry point.

Boots the serving subsystem (``ml_recipe_tpu/serve/``): load model +
checkpoint, build the bucket grid, warm every bucket program through the
autotune cache (a warm restart performs zero probes), pre-flight each
bucket against device HBM (shrinking the grid instead of OOMing
mid-traffic), then serve ``POST /v1/qa`` until SIGTERM drains it.

Usage::

    python -m ml_recipe_tpu.cli.serve -c config/serve.cfg

No reference counterpart: the reference stack (and this repo's
``cli/validate.py``) is an offline batch predictor; this is the long-running
request/response engine the ROADMAP's "serves heavy traffic" north star
needs.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

from ..compose import init_model
from ..config.parser import get_model_parser, get_params, get_serve_parser
from ..ops import aot, autotune
from ..parallel import ParallelPlan
from ..utils.logging import get_logger, show_params


def main(params, model_params) -> int:
    from ..serve.bucketing import BucketGrid
    from ..serve.engine import QAEngine
    from ..serve.server import QAServer

    show_params(model_params, "model")
    show_params(params, "serve")

    autotune.configure(
        enabled=params.autotune, cache_dir=params.autotune_cache
    )
    # AOT program-store wiring (ops/aot.py): a rolling-restart replacement
    # engine deserializes every bucket program from the shared store
    # instead of recompiling the grid
    aot.configure(
        enabled=params.aot_cache != "off",
        cache_dir=(
            params.aot_cache if params.aot_cache not in (None, "off")
            else None),
        cache_bytes=params.aot_cache_bytes or None,
    )

    # --trace_spans: structured request-lifecycle spans (admission ->
    # queue -> flush -> device -> span_reduce -> respond, keyed by request
    # id) as Chrome trace-event JSON, written out when the drain completes
    tracer = None
    if getattr(params, "trace_spans", None):
        from ..metrics import trace as trace_mod

        tracer = trace_mod.install(trace_mod.TraceWriter(
            str(Path(params.trace_spans) / f"serve_trace_{os.getpid()}.json"),
            process_name="serve",
        ))

    model, model_state, tokenizer = init_model(
        model_params, checkpoint=params.checkpoint,
        quantize=getattr(params, "quantize", "off"),
    )
    # one declarative plan from --mesh; the engine derives its bucket
    # placements from it
    mesh = ParallelPlan.from_spec(getattr(params, "mesh", None)).mesh

    engine = QAEngine(
        model,
        model_state,
        tokenizer,
        grid=BucketGrid.from_spec(params.buckets),
        mesh=mesh,
        max_batch_delay_ms=params.max_batch_delay_ms,
        queue_size=params.queue_size,
        max_question_len=params.max_question_len,
        doc_stride=params.doc_stride,
        quantize=getattr(params, "quantize", "off"),
        serve_cache_bytes=getattr(params, "serve_cache_bytes", 0),
        doc_cache_bytes=getattr(params, "doc_cache_bytes", 0),
        long_scatter_chunks=getattr(params, "long_scatter_chunks", 0),
    )
    engine.warmup(hbm_preflight=params.hbm_preflight)

    server = QAServer(
        engine,
        host=params.host,
        port=params.port,
        request_timeout_s=params.request_timeout_s,
        drain_timeout_s=params.drain_timeout_s,
    )
    server.install_signal_handlers()
    server.start()

    if params.ready_file:
        # orchestration hook (supervisor, chaos drills): the listener is up
        # and every bucket is compiled — traffic is safe to send
        ready = Path(params.ready_file)
        tmp = ready.with_name(ready.name + ".tmp")
        tmp.write_text(json.dumps({
            "host": server.host, "port": server.port, "pid": os.getpid(),
            "buckets": [str(b) for b in engine.grid],
        }))
        os.replace(tmp, ready)

    try:
        server.wait()
    finally:
        server.shutdown()
        if tracer is not None:
            from ..metrics import trace as trace_mod

            trace_mod.install(None)
            tracer.close()
    return 0


def cli() -> None:
    from ..utils.platform import honor_env_platform

    honor_env_platform()
    _, (params, model_params) = get_params((get_serve_parser, get_model_parser))
    get_logger(logger_name="serve")

    raise SystemExit(main(params, model_params))


if __name__ == "__main__":
    cli()
