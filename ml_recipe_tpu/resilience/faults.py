"""Deterministic fault-injection registry (``FaultPlan``).

Every recovery path in the codebase — torn-save rollback, loader retries,
watchdog abort + supervised restart — is only trustworthy if it can be
driven on demand. A :class:`FaultPlan` names WHERE a fault fires (a site
threaded through the real code path), WHAT it does (kill / raise / stall /
slow) and WHEN (the nth arrival at the site), so a whole kill-restart-resume
scenario replays identically run after run: no randomness, no timing races.

Spec grammar (semicolon-separated entries)::

    site:kind[@hit][xcount][~seconds][!once][%hostN]

- ``site``   one of :data:`KNOWN_SITES` (typos are a hard error — a drill
  that silently never fires is worse than no drill).
- ``kind``   ``kill``  — ``os._exit(KILL_EXIT_CODE)``: a hard kill, no
  atexit/finally, exactly what preemption or an OOM kill looks like;
  ``raise`` — raise :class:`FaultError` (an ``OSError``, so transient-IO
  retry paths see it as the real thing); ``stall`` — block ``~seconds``
  (default 3600: long enough that only a watchdog ends it); ``slow`` —
  sleep ``~seconds`` (default 0.05) and continue.
- ``@hit``   1-based arrival index at which the fault starts firing
  (default 1).
- ``xcount`` number of consecutive arrivals that fire (default 1;
  ``x*`` = every arrival from ``@hit`` on).
- ``!once``  fire at most once across PROCESS RESTARTS, tracked via a
  marker file under ``$MLRT_FAULT_STATE`` — the knob that makes
  kill-then-recover drills converge instead of crash-looping (without the
  env var, ``!once`` is per-process only). Markers are keyed per host, so
  a shared state dir never cross-suppresses hosts.
- ``%hostN`` scope the spec to the process whose ``$MLRT_HOST`` equals N
  (the elastic supervisor stamps every child with its host id) — what
  makes multi-host chaos drills deterministic: ``trainer.step:kill@4%host1``
  kills exactly host 1's child on its 4th step, nobody else's. A
  malformed scope (``%h1``, ``%host``) is a hard parse error: a drill
  that silently never fires is worse than no drill. Arrival counters
  still advance on every host — only the ACTION is scoped.

Plans come from ``--fault_plan`` (config/CLI) or the ``MLRT_FAULTS`` env
var (read lazily on first :func:`fire`, so supervised child processes and
shell drills need no code changes). Example::

    MLRT_FAULTS='ckpt.pre_manifest:kill@2!once;loader.read:raise@1x3'
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

FAULT_ENV = "MLRT_FAULTS"
FAULT_STATE_ENV = "MLRT_FAULT_STATE"

# This process's host id in a multi-host pod (the elastic supervisor sets
# it on every child). Read lazily at fire time: %hostN scoping and the
# per-host !once marker key both resolve against it; unset means host 0.
HOST_ENV = "MLRT_HOST"


def current_host() -> int:
    """This process's pod host id (``$MLRT_HOST``, default 0)."""
    raw = os.environ.get(HOST_ENV, "")
    try:
        return int(raw) if raw else 0
    except ValueError:
        logger.warning(f"Ignoring malformed ${HOST_ENV}={raw!r}; using host 0.")
        return 0

# Exit code of an injected `kill` — distinct from the watchdog's so the
# supervisor's classification (and test assertions) can tell a drill kill
# from a hang abort.
KILL_EXIT_CODE = 89

# The injection sites threaded through the codebase. A FaultPlan naming
# anything else fails at parse time.
KNOWN_SITES = (
    "ckpt.pre_write",        # single-file save: before the atomic write
    "ckpt.pre_shard_write",  # sharded save: before this host's shard file
    "ckpt.pre_manifest",     # sharded save: shards landed, manifest not yet
    "ckpt.mid_swap",         # sharded save: between the swap's two renames
    "checkpoint.persist",    # persist leg (serialize+write) of any save —
                             # on the BACKGROUND thread under
                             # --async_checkpoint, so a kill here is the
                             # canonical crash-mid-persist drill
    "loader.read",           # every dataset item read (both loaders)
    "loader.prefetch",       # device-prefetch thread, per staged batch
    "dist.rendezvous",       # before jax.distributed.initialize
    "dist.barrier",          # inside every named cross-process barrier
    "trainer.step",          # host side of each train step
    "trainer.eval_step",     # host side of each eval step
    "fleet.engine",          # serving engine, per admitted /v1/qa request
                             # (fleet chaos drills: kill one engine of a
                             # router tier mid-load; scope with %hostN —
                             # the fleet manager stamps MLRT_HOST with the
                             # engine index)
)

_KINDS = ("kill", "raise", "stall", "slow")

_DEFAULT_SECONDS = {"stall": 3600.0, "slow": 0.05}


class FaultError(OSError):
    """An injected fault. Subclasses ``OSError`` on purpose: transient-IO
    retry paths must treat a drill exactly like the failure it simulates."""


@dataclasses.dataclass
class FaultSpec:
    site: str
    kind: str
    hit: int = 1
    count: int = 1          # -1 = every arrival from `hit` on
    seconds: Optional[float] = None
    once: bool = False
    host: Optional[int] = None   # %hostN scope; None = every host

    def active_at(self, n: int) -> bool:
        if n < self.hit:
            return False
        return self.count < 0 or n < self.hit + self.count


_SPEC_RE = re.compile(
    r"^(?P<site>[\w.]+):(?P<kind>\w+)(?P<rest>(?:@\d+|x(?:\d+|\*)|~[\d.]+|!once|%\w+)*)$"
)
_TOKEN_RE = re.compile(r"@\d+|x(?:\d+|\*)|~[\d.]+|!once|%\w+")
_HOST_SCOPE_RE = re.compile(r"^%host(\d+)$")


def _parse_entry(entry: str) -> FaultSpec:
    m = _SPEC_RE.match(entry.strip())
    if m is None:
        raise ValueError(
            f"malformed fault spec {entry!r}; expected "
            f"'site:kind[@hit][xcount][~seconds][!once][%hostN]'"
        )
    site, kind, rest = m.group("site"), m.group("kind"), m.group("rest")
    if site not in KNOWN_SITES:
        raise ValueError(
            f"unknown fault site {site!r}; known sites: {', '.join(KNOWN_SITES)}"
        )
    if kind not in _KINDS:
        raise ValueError(
            f"unknown fault kind {kind!r} in {entry!r}; known kinds: "
            f"{', '.join(_KINDS)}"
        )
    spec = FaultSpec(site=site, kind=kind)
    for tok in _TOKEN_RE.findall(rest):
        if tok.startswith("@"):
            spec.hit = int(tok[1:])
        elif tok.startswith("x"):
            spec.count = -1 if tok[1:] == "*" else int(tok[1:])
        elif tok.startswith("~"):
            spec.seconds = float(tok[1:])
        elif tok == "!once":
            spec.once = True
        elif tok.startswith("%"):
            scope = _HOST_SCOPE_RE.match(tok)
            if scope is None:
                raise ValueError(
                    f"malformed host scope {tok!r} in fault spec {entry!r}; "
                    f"expected '%host<N>' as the LAST token (e.g. "
                    f"'trainer.step:kill@4%host1')"
                )
            spec.host = int(scope.group(1))
    if spec.hit < 1:
        raise ValueError(f"fault spec {entry!r}: @hit is 1-based")
    return spec


class FaultPlan:
    """A parsed set of :class:`FaultSpec` with per-site arrival counters.

    Counters are plain per-process integers (thread-safe), so a plan is
    deterministic by construction: the nth arrival at a site is the nth
    arrival, every run. ``!once`` specs additionally consult a marker file
    under ``state_dir`` so they stay fired across supervised restarts.
    """

    def __init__(
        self, specs: List[FaultSpec], *, state_dir: Optional[str] = None
    ):
        self.specs = list(specs)
        self.state_dir = state_dir
        self._by_site: Dict[str, List[Tuple[int, FaultSpec]]] = {}
        for i, spec in enumerate(self.specs):
            self._by_site.setdefault(spec.site, []).append((i, spec))
        self._counters: Dict[str, int] = {}
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, text: str, *, state_dir: Optional[str] = None) -> "FaultPlan":
        entries = [e for e in (text or "").split(";") if e.strip()]
        return cls([_parse_entry(e) for e in entries], state_dir=state_dir)

    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        text = os.environ.get(FAULT_ENV)
        if not text:
            return None
        return cls.parse(text, state_dir=os.environ.get(FAULT_STATE_ENV))

    def __bool__(self) -> bool:
        return bool(self.specs)

    def hits(self, site: str) -> int:
        with self._lock:
            return self._counters.get(site, 0)

    # -- !once cross-restart state -------------------------------------------

    def _marker(self, index: int, spec: FaultSpec) -> Optional[str]:
        if self.state_dir is None:
            return None
        # keyed per host: elastic drills share one state dir across the
        # whole pod, and host 0's kill must not suppress host 1's
        return os.path.join(
            self.state_dir,
            f"fired-{index:02d}-{spec.site}.{spec.kind}.h{current_host()}",
        )

    def _already_fired(self, index: int, spec: FaultSpec) -> bool:
        marker = self._marker(index, spec)
        return marker is not None and os.path.exists(marker)

    def _record_fired(self, index: int, spec: FaultSpec) -> None:
        marker = self._marker(index, spec)
        if marker is None:
            return
        os.makedirs(self.state_dir, exist_ok=True)
        # write BEFORE acting: a `kill` never returns, and the whole point
        # of !once is that the restarted process does not re-fire it
        with open(marker, "w") as fh:
            fh.write(f"hit={self._counters.get(spec.site, 0)}\n")

    # -- firing ---------------------------------------------------------------

    def fire(self, site: str) -> None:
        """Arrival at ``site``: bump the counter and act on any armed spec.

        The !once check-and-record happens under the plan lock: concurrent
        loader threads arriving inside the active window must resolve to
        exactly ONE firing (the determinism contract), not one each.
        """
        armed = self._by_site.get(site)
        if not armed:
            return
        host = current_host()
        with self._lock:
            n = self._counters.get(site, 0) + 1
            self._counters[site] = n
            to_fire = []
            for index, spec in armed:
                if spec.host is not None and spec.host != host:
                    continue  # scoped to another host; counter still advanced
                if not spec.active_at(n):
                    continue
                if spec.once:
                    if self._already_fired(index, spec):
                        continue
                    self._record_fired(index, spec)
                to_fire.append(spec)
        # act OUTSIDE the lock: stall/raise/kill must not wedge other
        # threads' (non-firing) site arrivals behind the mutex
        for spec in to_fire:
            self._act(spec, n)

    def _act(self, spec: FaultSpec, n: int) -> None:
        note = f"FAULT: {spec.kind} at {spec.site} (arrival {n})"
        if spec.kind == "kill":
            # bypass logging: mimic a hard kill as closely as a self-
            # inflicted one can — the only courtesy is one stderr line so
            # drill logs show what happened
            sys.stderr.write(note + f" -> os._exit({KILL_EXIT_CODE})\n")
            sys.stderr.flush()
            os._exit(KILL_EXIT_CODE)
        if spec.kind == "raise":
            logger.warning(note)
            raise FaultError(f"injected fault at {spec.site} (arrival {n})")
        seconds = (
            spec.seconds if spec.seconds is not None
            else _DEFAULT_SECONDS[spec.kind]
        )
        logger.warning(f"{note} for {seconds:g}s")
        time.sleep(seconds)


# -- process-global plan -------------------------------------------------------

_plan: Optional[FaultPlan] = None
_env_checked = False


def install_plan(plan) -> Optional[FaultPlan]:
    """Install the process-global plan: a :class:`FaultPlan`, a spec string,
    or ``None`` to disarm (also stops the lazy env-var lookup)."""
    global _plan, _env_checked
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan, state_dir=os.environ.get(FAULT_STATE_ENV))
    _plan = plan
    _env_checked = True
    if _plan:
        logger.warning(
            f"Fault plan armed: {len(_plan.specs)} spec(s) at sites "
            f"{sorted({s.site for s in _plan.specs})}."
        )
    return _plan


def active_plan() -> Optional[FaultPlan]:
    global _plan, _env_checked
    if not _env_checked:
        _env_checked = True
        _plan = FaultPlan.from_env()
        if _plan:
            logger.warning(f"Fault plan armed from ${FAULT_ENV}.")
    return _plan


def fire(site: str) -> None:
    """Hot-path entry: a no-op (one None check) unless a plan is armed."""
    plan = active_plan()
    if plan is not None:
        plan.fire(site)


# -- shared transient-retry helper ---------------------------------------------


def retry_transient(
    fn: Callable,
    *,
    retries: int = 3,
    base_delay: float = 0.05,
    factor: float = 2.0,
    exceptions: tuple = (OSError,),
    what: str = "operation",
    sleep: Callable[[float], None] = time.sleep,
):
    """Run ``fn()`` with bounded retry + exponential backoff on transient
    errors. ``retries`` counts RE-tries: the last failure (attempt
    ``retries + 1``) propagates to the caller with its original traceback.
    """
    delay = base_delay
    for attempt in range(retries + 1):
        try:
            return fn()
        except exceptions as e:
            if attempt == retries:
                raise
            logger.warning(
                f"Transient failure in {what} (attempt {attempt + 1}/"
                f"{retries + 1}): {e!r}; retrying in {delay:.2f}s."
            )
            sleep(delay)
            delay *= factor
