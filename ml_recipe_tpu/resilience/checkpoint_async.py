"""Async overlapped checkpointing: persist on a background thread.

A checkpoint save has two legs with very different costs on the step
critical path: the device -> host SNAPSHOT (one bounded copy of the live
state, which must block training — the next step donates those buffers)
and the PERSIST tail (serialize + write + atomic swap), which scales with
state size and touches nothing the training step needs. The sync path
pays both on the critical path; ``--async_checkpoint`` pays only the
snapshot and runs the persist here, on a dedicated thread, in the
TorchTitan distributed-checkpoint shape (arxiv 2410.06511):

- at most ONE persist is in flight: :meth:`submit` implicitly waits for
  the previous one (the completion barrier before the next save), so two
  saves can never interleave their writes to one path;
- :meth:`wait` is the explicit completion barrier the trainer arms before
  restores, at exit, and before a SIGTERM resume hands the checkpoint to
  the supervisor — a persist error is re-raised there (wrapped in
  :class:`AsyncCheckpointError`), never swallowed;
- the worker is a NON-daemon thread, so even a caller that forgets the
  exit barrier gets the interpreter's thread-join at shutdown instead of
  a torn tmp file (hard kills are covered by the persist functions'
  atomic rename discipline: the previous valid checkpoint stays newest);
- the ``checkpoint.persist`` fault site fires at the top of every persist
  (``resilience.faults``), so a kill-mid-persist drill exercises exactly
  this thread.

The persist callable itself comes from ``train.checkpoint``
(``persist_state`` / ``persist_state_sharded``) — the background writer
reuses the same per-leaf crc32 and tmp+rename helpers as the sync path,
not a parallel implementation.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

logger = logging.getLogger(__name__)


class AsyncCheckpointError(RuntimeError):
    """A background checkpoint persist failed. Raised at the NEXT
    completion barrier (the following save, an explicit ``wait``, or
    process exit) with the original exception chained — an async save
    failure must surface where the caller can still act on it, not
    vanish into a thread log."""


class AsyncCheckpointer:
    """Single-flight background persist executor for checkpoint saves."""

    def __init__(self, *, name: str = "async-checkpoint"):
        self.name = name
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._pending_path: Optional[str] = None
        self._error: Optional[tuple] = None  # (path, exception)
        # perf_counter stamp of a wait() currently blocked on the
        # in-flight persist, or None: lets the worker report how much of
        # its persist wall the main thread spent STALLED waiting for it —
        # that share did not overlap training and must not be booked as
        # overlapped time (it is already on the caller's critical path)
        self._wait_started: Optional[float] = None

    def pending(self) -> bool:
        """True while a persist is in flight (its thread is alive)."""
        with self._lock:
            thread = self._thread
        return thread is not None and thread.is_alive()

    def wait(self, *, raise_errors: bool = True) -> None:
        """Block until any in-flight persist lands; re-raise its failure.

        ``raise_errors=False`` (best-effort paths: an exception is
        already propagating, or an emergency save is about to run and
        must not be aborted by a STALE failure) logs the failure at ERROR
        instead. Either way the error is consumed — it has been surfaced
        once, and re-raising it later would abort a save it has nothing
        to do with (e.g. the SIGTERM interrupt checkpoint).
        """
        with self._lock:
            thread = self._thread
            if thread is not None and thread.is_alive():
                self._wait_started = time.perf_counter()
        if thread is not None:
            thread.join()
            with self._lock:
                self._wait_started = None
                if self._thread is thread:
                    self._thread = None
                    self._pending_path = None
        with self._lock:
            error, self._error = self._error, None
        if error is None:
            return
        path, exc = error
        if raise_errors:
            raise AsyncCheckpointError(
                f"background checkpoint persist to {path} failed: "
                f"{type(exc).__name__}: {exc}"
            ) from exc
        logger.error(
            f"Background checkpoint persist to {path} failed: {exc!r} "
            f"(not re-raised: a best-effort barrier consumed it)."
        )

    def submit(
        self,
        path,
        persist_fn: Callable[[], None],
        *,
        on_done: Optional[Callable[[float, float], None]] = None,
    ) -> None:
        """Run ``persist_fn`` on the background thread.

        Waits for the previous persist first (single-flight — the
        caller's snapshot is already taken, so this wait is part of the
        save's blocking time and is what keeps writes to one path
        ordered). ``on_done(persist_s, stalled_s)`` is called from the
        worker thread on success: ``persist_s`` is the persist wall time,
        ``stalled_s`` the share of it the main thread spent blocked in
        :meth:`wait` on THIS persist — the genuinely overlapped time is
        their difference (stalled time is already on the caller's
        critical path and must not be double-booked as overlap).
        """
        self.wait()
        path = str(path)

        def run() -> None:
            t0 = time.perf_counter()
            try:
                persist_fn()
            except BaseException as e:  # noqa: BLE001 - recorded, re-raised at wait()
                with self._lock:
                    self._error = (path, e)
                logger.error(
                    f"ASYNC CHECKPOINT: persist to {path} failed on the "
                    f"background thread: {e!r} (will re-raise at the next "
                    f"completion barrier)."
                )
                return
            if on_done is not None:
                end = time.perf_counter()
                with self._lock:
                    waited = self._wait_started
                stalled = end - waited if waited is not None else 0.0
                try:
                    on_done(end - t0, max(0.0, stalled))
                except Exception as e:  # noqa: BLE001 - telemetry must not fail the save
                    logger.warning(
                        f"ASYNC CHECKPOINT: on_done callback failed: {e!r}"
                    )

        # non-daemon: a forgotten exit barrier degrades to the
        # interpreter's clean thread join, not a torn write. START before
        # publishing: a signal (SIGTERM->KeyboardInterrupt) landing
        # between the two lines must leave a RUNNING untracked persist
        # (joined by the interpreter at exit, writes atomic) rather than
        # a tracked never-started thread whose join() would raise and
        # abort the emergency save.
        thread = threading.Thread(target=run, name=self.name, daemon=False)
        thread.start()
        with self._lock:
            self._thread = thread
            self._pending_path = path
