"""Shared pod coordination for elastic supervision.

One supervisor per host is blind: when a PEER host dies mid-collective,
every surviving child wedges inside the rendezvous/all-reduce until a
multi-minute timeout fires, and nothing tells the survivors' supervisors
why. This module is the cross-host signal plane that fixes that, built
from the same primitives the rest of the observability plane trusts:
per-host JSON files written atomically (tmp + rename, so a reader never
sees a torn document on a local filesystem) in ONE shared directory under
the experiment dir.

Protocol (one file per host, ``pod/host-<N>.json``):

- every supervisor periodically ``publish()``-es its own file: schema
  version, status (``running`` / ``restarting`` / ``done`` / ``failed``),
  the pod ``generation``, its attempt index, a wall-clock heartbeat stamp
  and the child's last reported step (the straggler signal);
- the child (trainer) side beats through ``write_child_heartbeat``
  (wired off the step watchdog), so a host's published step advances at
  training cadence, not just supervisor-poll cadence;
- supervisors read every peer file with :func:`read_coordination_json` —
  the ONE guarded reader (graftlint MLA010 enforces this): absence is a
  protocol signal returned immediately, a torn/unparsable read is retried
  with bounded backoff (shared filesystems expose mid-replace windows)
  and only then degraded to None, and a schema mismatch raises — an old
  sidecar must be rejected loudly, never misread quietly.

Generation protocol: the pod generation is a monotonically increasing
restart epoch. Any supervisor that decides the pod must restart (its own
child crashed, or it declared a peer host dead) bumps the generation and
publishes it; every other supervisor that observes a generation above its
own kills its child immediately and restarts at the new generation. That
single rule is what turns N independent retry loops into one coordinated
elastic pod — no leader, no extra channel.

Everything here is stdlib-only: the supervisor must not pay the jax
import (same contract as :mod:`.supervisor` and :mod:`..metrics.goodput`).
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Dict, Optional, Sequence

from ..metrics.artifacts import atomic_write_json, wall_now

logger = logging.getLogger(__name__)

# Directory (under the experiment dir) holding the per-host files.
COORD_DIRNAME = "pod"

# Bump on ANY incompatible change to the documents below. A reader that
# meets another version raises CoordinationSchemaError: a pod where half
# the hosts run an older build must fail loudly at the first read, not
# half-coordinate.
COORD_SCHEMA_VERSION = 1

_HOST_FILE = "host-{host:03d}.json"
_CHILD_FILE = "child-{host:03d}.json"

# Environment override the elastic supervisor sets in every child:
# "<world_size>:<process_id>" for the CURRENT live world, so a shrunk pod
# re-forms without argv rewrites (parallel/dist.py honors it before the
# params-derived topology). Defined here — not in parallel.dist — so the
# supervisor can import it without paying the jax import.
ELASTIC_WORLD_ENV = "MLRT_ELASTIC_WORLD"


class CoordinationSchemaError(RuntimeError):
    """A coordination/sidecar document carries a different (or missing)
    schema version — written by an incompatible build."""


def read_coordination_json(
    path,
    *,
    retries: int = 3,
    base_delay: float = 0.05,
    factor: float = 2.0,
    sleep=time.sleep,
) -> Optional[dict]:
    """THE guarded read for supervisor/coordination JSON (MLA010).

    - Absent file -> ``None`` immediately: absence is a protocol state (a
      host that has not published yet), not an error to retry.
    - Torn or unparsable content -> bounded retry with exponential
      backoff. Writers are atomic, but shared filesystems (NFS close-to-
      open, object-store gateways) still expose transient windows; a
      transient torn read must NOT be reported as a dead host. After the
      budget it degrades to ``None`` with a warning.
    - Schema mismatch (missing or different ``schema`` field) -> raises
      :class:`CoordinationSchemaError`. An old sidecar is a deployment
      error to surface, never data to act on.
    """
    path = os.fspath(path)
    delay = base_delay
    for attempt in range(retries + 1):
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            if attempt == retries:
                logger.warning(
                    f"COORD: unreadable after {retries + 1} attempt(s): "
                    f"{path}: {e!r}; treating as absent."
                )
                return None
            logger.warning(
                f"COORD: torn read of {path} (attempt {attempt + 1}/"
                f"{retries + 1}): {e!r}; retrying in {delay:.2f}s."
            )
            sleep(delay)
            delay *= factor
            continue
        if not isinstance(doc, dict):
            logger.warning(f"COORD: non-object document in {path}; ignoring.")
            return None
        schema = doc.get("schema")
        if schema != COORD_SCHEMA_VERSION:
            raise CoordinationSchemaError(
                f"{path} carries schema {schema!r}, this build requires "
                f"{COORD_SCHEMA_VERSION} — written by an incompatible "
                f"(older?) build; refusing to interpret it."
            )
        return doc
    return None


def write_child_heartbeat(coord_dir, host: int, *, step: Optional[int]) -> None:
    """The trainer-side beat (wired off the watchdog's ``add_on_beat``):
    the child's last completed step plus a wall stamp. Failures degrade
    heartbeating, never training."""
    path = os.path.join(os.fspath(coord_dir), _CHILD_FILE.format(host=int(host)))
    doc = {
        "schema": COORD_SCHEMA_VERSION,
        "host": int(host),
        "pid": os.getpid(),
        "step": None if step is None else int(step),
        "heartbeat": wall_now(),
    }
    try:
        atomic_write_json(path, doc)
    except OSError as e:
        logger.warning(f"COORD: could not write child heartbeat {path}: {e}")


class PodCoordinator:
    """This host's handle on the shared coordination directory.

    Thin by design: it publishes THIS host's document atomically and reads
    peers' documents through the guarded reader. All policy — staleness
    thresholds, generation adoption, who restarts whom — lives in the
    :class:`~.supervisor.ElasticSupervisor`, where it is unit-testable
    against hand-written peer files.
    """

    def __init__(self, coord_dir, *, host: int, n_hosts: int,
                 read_retries: int = 3, sleep=time.sleep):
        self.coord_dir = os.fspath(coord_dir)
        self.host = int(host)
        self.n_hosts = max(1, int(n_hosts))
        self.read_retries = int(read_retries)
        self._sleep = sleep

    # -- paths -----------------------------------------------------------------

    def host_path(self, host: int) -> str:
        return os.path.join(self.coord_dir, _HOST_FILE.format(host=int(host)))

    def child_path(self, host: int) -> str:
        return os.path.join(self.coord_dir, _CHILD_FILE.format(host=int(host)))

    # -- writes ----------------------------------------------------------------

    def publish(
        self,
        status: str,
        *,
        generation: int,
        attempt: int,
        step: Optional[int] = None,
        exit_class: Optional[str] = None,
        live_hosts: Optional[Sequence[int]] = None,
    ) -> None:
        """Atomically publish this host's document. A publish failure is
        logged and swallowed: a transient FS error must not kill the
        supervisor — peers only misread us if it PERSISTS, which is
        exactly the host-lost signal."""
        doc = {
            "schema": COORD_SCHEMA_VERSION,
            "host": self.host,
            "pid": os.getpid(),
            "status": str(status),
            "generation": int(generation),
            "attempt": int(attempt),
            "step": None if step is None else int(step),
            "exit_class": exit_class,
            "live_hosts": None if live_hosts is None else list(live_hosts),
            "heartbeat": wall_now(),
        }
        try:
            atomic_write_json(self.host_path(self.host), doc)
        except OSError as e:
            logger.warning(
                f"COORD: host {self.host} could not publish "
                f"{self.host_path(self.host)}: {e}"
            )

    # -- reads -----------------------------------------------------------------

    def peer_state(self, host: int) -> Optional[dict]:
        """One peer's document through the guarded reader (None when the
        peer has not published / the file degraded to unreadable).
        Schema mismatches propagate: see :func:`read_coordination_json`."""
        return read_coordination_json(
            self.host_path(host), retries=self.read_retries, sleep=self._sleep
        )

    def child_step(self, host: int) -> Optional[int]:
        """The child-side heartbeat step for ``host`` (None when the child
        never beat, or the file degraded)."""
        try:
            doc = read_coordination_json(
                self.child_path(host), retries=self.read_retries,
                sleep=self._sleep,
            )
        except CoordinationSchemaError as e:
            logger.error(f"COORD: rejecting child heartbeat: {e}")
            return None
        if doc is None:
            return None
        step = doc.get("step")
        return int(step) if isinstance(step, (int, float)) else None

    def peer_states(self) -> Dict[int, Optional[dict]]:
        """Every OTHER host's document, keyed by host id."""
        return {
            h: self.peer_state(h)
            for h in range(self.n_hosts)
            if h != self.host
        }
