"""Auto-resume supervisor: the local analogue of an elastic agent.

Wraps the training entrypoint in a bounded-retry loop (``--supervise`` on
the train CLI). Each attempt is a child process; on exit the supervisor

1. classifies the exit — clean / preempted / hang (watchdog abort) /
   crash — from the return code,
2. measures progress by peeking ``global_step`` out of the newest on-disk
   checkpoint (no cooperation from the child needed: a hard-killed child
   reports through what it durably saved, which is the only truth anyway),
3. restarts with ``--last <newest checkpoint>`` after an exponential
   backoff with seeded jitter (deterministic: drills replay identically),
4. aborts with a diagnosis once ``crash_loop_window`` consecutive failed
   attempts made NO checkpoint progress — a crash-loop restarted forever
   is strictly worse than a loud early exit with the failure classified.

The supervisor deliberately knows nothing about JAX: it manages a process
and a checkpoint directory. That is what lets the chaos suite drive real
kill/stall scenarios through it in milliseconds-per-decision on CPU.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import signal
import subprocess
import sys
import time
from datetime import datetime, timezone
from typing import Callable, List, Optional, Sequence

from .coordination import (
    COORD_DIRNAME,
    COORD_SCHEMA_VERSION,
    ELASTIC_WORLD_ENV,
    CoordinationSchemaError,
    PodCoordinator,
    read_coordination_json,
)
from .faults import HOST_ENV
from .watchdog import WATCHDOG_EXIT_CODE

logger = logging.getLogger(__name__)

# JSON sidecar the supervisor keeps current next to the checkpoints, so the
# training exporter (and humans) read restart counts / exit classifications
# / backoff state without parsing logs. Written atomically (tmp + rename):
# a reader never sees a torn document.
STATE_FILENAME = "supervisor_state.json"


def write_supervisor_state(path, state: dict) -> None:
    """Atomically persist the supervisor's observable state (schema-stamped:
    the elastic coordination plane reads these cross-host, and an old
    sidecar must be rejectable — see resilience/coordination.py)."""
    path = os.fspath(path)
    directory = os.path.dirname(path)
    if directory:
        os.makedirs(directory, exist_ok=True)
    doc = dict(state)
    doc.setdefault("schema", COORD_SCHEMA_VERSION)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=2)
    os.replace(tmp, path)


def peek_supervisor_state(path) -> Optional[dict]:
    """Best-effort read of the sidecar; None when absent or unreadable
    (an exporter scrape must never crash on a mid-replace race or a
    corrupt file). Routed through the guarded coordination reader: a
    TRANSIENT torn read (shared-FS mid-replace window) is retried with
    bounded backoff instead of being misreported as absent, and a sidecar
    written by an incompatible build is rejected loudly."""
    try:
        return read_coordination_json(path)
    except CoordinationSchemaError as e:
        logger.error(f"SUPERVISOR: rejecting sidecar: {e}")
        return None

# A supervised child that caught SIGTERM/SIGINT, saved interrupt.ch and
# unwound cleanly exits with this (EX_TEMPFAIL) instead of 0, so the
# supervisor restarts it — a preemption is a reason to resume, not to stop.
PREEMPT_EXIT_CODE = 75

CLEAN = "clean"
PREEMPTED = "preempted"
HANG = "hang"
CRASH = "crash"
# elastic-only outcomes: the SUPERVISOR killed its (healthy) child because
# the pod had to re-form — a peer bumped the restart generation
# (POD_RESTART) or a peer host's heartbeat went stale / it self-reported
# failed (HOST_LOST). Neither is this host failing, so neither consumes
# the restart budget (the at-fault host's own supervisor bounds ITS loop).
POD_RESTART = "pod-restart"
HOST_LOST = "host-lost"

# exits worth retrying; CLEAN ends the loop, anything unknown is a crash
_RETRYABLE = (PREEMPTED, HANG, CRASH, POD_RESTART, HOST_LOST)

# coordinated-restart outcomes: retryable, but exempt from the no-progress
# budget/crash-loop accounting (see above)
_COORDINATED = (POD_RESTART, HOST_LOST)


def classify_exit(returncode: int) -> str:
    """Map a child return code onto an exit class."""
    if returncode == 0:
        return CLEAN
    if returncode == WATCHDOG_EXIT_CODE:
        return HANG
    if returncode == PREEMPT_EXIT_CODE:
        return PREEMPTED
    # Popen reports death-by-signal as -signum; platform evictions that
    # skip our SIGTERM hook surface as SIGKILL/SIGTERM here. 128+signum
    # covers shells that re-encode it. An injected drill kill
    # (KILL_EXIT_CODE) stays a crash: mid-write kills are the scenario
    # being tested, not an infra event to blame.
    for sig in (signal.SIGTERM, signal.SIGKILL, signal.SIGHUP):
        if returncode in (-int(sig), 128 + int(sig)):
            return PREEMPTED
    return CRASH


@dataclasses.dataclass
class RetryPolicy:
    # Restarts chargeable AFTER the first attempt. Only failures WITHOUT
    # checkpoint progress consume the budget: on preemptible pools a
    # healthy multi-day run is preempted far more than any fixed budget,
    # and a preemption that resumed and advanced global_step is the system
    # WORKING, not failing. Pathological progress-making crash cycles are
    # still bounded by the crash-loop detector the moment progress stops.
    max_restarts: int = 5
    backoff_base: float = 1.0      # seconds before restart #1
    backoff_factor: float = 2.0
    backoff_max: float = 30.0
    jitter: float = 0.1            # +-10% seeded jitter (thundering herd)
    crash_loop_window: int = 3     # consecutive no-progress failures -> abort
    seed: int = 0


@dataclasses.dataclass
class Attempt:
    index: int
    returncode: int
    outcome: str
    step_before: Optional[int]
    step_after: Optional[int]
    backoff: float = 0.0           # sleep AFTER this attempt (0 = none)

    @property
    def progressed(self) -> bool:
        if self.step_after is None:
            return False
        return self.step_before is None or self.step_after > self.step_before


@dataclasses.dataclass
class SupervisorResult:
    status: str        # 'clean' | 'crash-loop' | 'retries-exhausted' | 'terminated'
    attempts: List[Attempt]
    diagnosis: str = ""
    signum: Optional[int] = None   # set when status == 'terminated'

    @property
    def exit_code(self) -> int:
        if self.signum is not None:
            return 128 + int(self.signum)  # shell convention: died by signal
        return {"clean": 0, "crash-loop": 1}.get(self.status, 2)

    def outcomes(self) -> List[str]:
        return [a.outcome for a in self.attempts]


class Supervisor:
    """Bounded-retry loop around a launchable child.

    ``launch(attempt_index)`` returns either a ``Popen``-like object (with
    ``wait``/``kill``) or a bare int return code (tests). ``progress()``
    returns the newest durable ``global_step`` (or None) — called before
    and after every attempt. ``sleep`` is injectable so drills don't
    actually wait out the backoff.
    """

    def __init__(
        self,
        launch: Callable[[int], object],
        *,
        progress: Callable[[], Optional[int]],
        policy: Optional[RetryPolicy] = None,
        attempt_timeout: Optional[float] = None,
        sleep: Callable[[float], None] = time.sleep,
        state_path=None,
        ledger_path=None,
        flight_dir=None,
    ):
        self.launch = launch
        self.progress = progress
        self.policy = policy or RetryPolicy()
        self.attempt_timeout = attempt_timeout
        self.sleep = sleep
        self.state_path = os.fspath(state_path) if state_path else None
        # goodput ledger (metrics/goodput.py): attempt boundaries appended
        # here partition restart downtime out of the run's wall-clock
        self.ledger_path = os.fspath(ledger_path) if ledger_path else None
        # flight-recorder dumps (metrics/flightrec.py) live here; the exit
        # classifier reads the newest one back into its diagnoses
        self.flight_dir = os.fspath(flight_dir) if flight_dir else None
        self._rng = random.Random(self.policy.seed)
        self._child = None
        self._terminate_signum: Optional[int] = None

    def _ledger_event(self, ev: str, **fields) -> None:
        """Append an attempt-boundary event to the goodput ledger; a
        failure degrades accounting, never supervision (same contract as
        the sidecar)."""
        if self.ledger_path is None:
            return
        from ..metrics.goodput import append_event

        try:
            append_event(self.ledger_path, ev, pid=os.getpid(), **fields)
        except OSError as e:
            logger.warning(
                f"SUPERVISOR: could not append {ev} to the goodput ledger "
                f"{self.ledger_path}: {e}"
            )

    def _flight_timeline(self) -> str:
        """The newest flight-record dump's last-K-step timeline, rendered
        for a diagnosis ('' when no recorder ran or nothing is readable)."""
        if self.flight_dir is None:
            return ""
        from ..metrics.flightrec import newest_flight_record, timeline_lines

        found = newest_flight_record(self.flight_dir)
        if found is None:
            return ""
        path, doc = found
        lines = timeline_lines(doc, last=8)
        if not lines:
            return ""
        return (
            f"\nFlight recorder ({os.path.basename(path)}, dumped on "
            f"{doc.get('reason', '?')}): last {len(lines)} event(s):\n"
            + "\n".join(lines)
        )

    def _persist_state(
        self,
        status: str,
        attempts: List["Attempt"],
        *,
        restarts_used: int = 0,
        no_progress_streak: int = 0,
    ) -> None:
        """Keep the JSON sidecar current; failures degrade observability,
        never the supervision loop itself."""
        if self.state_path is None:
            return
        last = attempts[-1] if attempts else None
        state = {
            "pid": os.getpid(),
            "status": status,
            "attempts": len(attempts),
            "restarts_used": restarts_used,
            "max_restarts": self.policy.max_restarts,
            "no_progress_streak": no_progress_streak,
            "crash_loop_window": self.policy.crash_loop_window,
            "outcomes": [a.outcome for a in attempts],
            "last_returncode": last.returncode if last else None,
            "last_outcome": last.outcome if last else None,
            "step": last.step_after if last else None,
            "last_backoff_s": last.backoff if last else 0.0,
            # wall-clock EVENT stamp (not an interval measurement): humans
            # and dashboards correlate this with logs and scrape times
            "updated_at": datetime.now(timezone.utc).isoformat(),
        }
        try:
            write_supervisor_state(self.state_path, state)
        except OSError as e:
            logger.warning(
                f"SUPERVISOR: could not persist state to "
                f"{self.state_path}: {e}"
            )

    # -- supervisor-level signals ----------------------------------------------

    def _forward_signal(self, signum, frame) -> None:
        """SIGTERM/SIGINT on the SUPERVISOR: forward to the live child (so
        it takes its own save-and-exit path) and stop supervising after it
        exits — never orphan a training process that would race the next
        submission's child on the checkpoint directory."""
        self._terminate_signum = int(signum)
        child = self._child
        if child is not None and hasattr(child, "send_signal"):
            try:
                child.send_signal(signum)
            except OSError:  # child already gone
                pass

    def _install_signal_handlers(self):
        import threading

        if threading.current_thread() is not threading.main_thread():
            return None  # signal.signal raises off the main thread
        prev = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            prev[sig] = signal.signal(sig, self._forward_signal)
        return prev

    # -- one attempt -----------------------------------------------------------

    def _wait(self, child) -> int:
        if isinstance(child, int):
            return child
        try:
            return child.wait(timeout=self.attempt_timeout)
        except subprocess.TimeoutExpired:
            # supervisor-side wall clock tripped: the child has no (working)
            # watchdog — kill it and classify as a hang ourselves
            logger.error(
                f"Attempt exceeded the {self.attempt_timeout:g}s wall clock; "
                f"killing the child."
            )
            child.kill()
            child.wait()
            return WATCHDOG_EXIT_CODE

    # -- elastic hook points (no-ops for the fixed-world supervisor) -----------

    def _pre_attempt(self, attempt_i: int):
        """Return ``(status, diagnosis)`` to abort supervision before
        launching attempt ``attempt_i``; None to proceed. The elastic
        subclass enforces the min-world floor here."""
        return None

    def _classify_outcome(self, rc: int) -> str:
        """Map a child return code onto an outcome. The elastic subclass
        overrides the classification when IT killed the child for a
        coordinated pod restart (the raw rc would read as 'preempted')."""
        return classify_exit(rc)

    def _post_attempt(self, attempt: "Attempt") -> None:
        """Called once per finished attempt, before retry/abort decisions.
        The elastic subclass publishes coordination state (and bumps the
        pod generation when this host's own child failed)."""

    def _backoff(self, no_progress_streak: int) -> float:
        """Backoff grows with CONSECUTIVE no-progress failures (a persistent
        fault deserves widening gaps); a restart after a progressing
        failure — a resumed preemption — waits only the base."""
        p = self.policy
        base = min(
            p.backoff_base * (p.backoff_factor ** max(no_progress_streak - 1, 0)),
            p.backoff_max,
        )
        return base * (1.0 + p.jitter * self._rng.uniform(-1.0, 1.0))

    # -- the loop --------------------------------------------------------------

    def run(self) -> SupervisorResult:
        prev_handlers = self._install_signal_handlers()
        try:
            return self._run()
        finally:
            if prev_handlers:
                for sig, handler in prev_handlers.items():
                    signal.signal(sig, handler)

    def _run(self) -> SupervisorResult:
        p = self.policy
        attempts: List[Attempt] = []
        no_progress_streak = 0
        restarts_used = 0  # only no-progress failures consume the budget

        def persist(status: str) -> None:
            self._persist_state(
                status, attempts,
                restarts_used=restarts_used,
                no_progress_streak=no_progress_streak,
            )

        def terminated(step) -> SupervisorResult:
            diagnosis = (
                f"SUPERVISOR: terminated by signal {self._terminate_signum} "
                f"(checkpoint step {step}); standing down without restart."
            )
            logger.error(diagnosis)
            sys.stderr.write(diagnosis + "\n")
            sys.stderr.flush()
            persist("terminated")
            return SupervisorResult(
                "terminated", attempts, diagnosis, signum=self._terminate_signum
            )

        persist("running")
        attempt_i = 0
        while True:
            abort = self._pre_attempt(attempt_i)
            if abort is not None:
                status, diagnosis = abort
                logger.error(diagnosis)
                sys.stderr.write(diagnosis + "\n")
                sys.stderr.flush()
                persist(status)
                return SupervisorResult(status, attempts, diagnosis)
            step_before = self.progress()
            if self._terminate_signum is not None:
                # signal arrived between attempts (e.g. during backoff):
                # do not launch another child
                return terminated(step_before)
            logger.warning(
                f"SUPERVISOR: attempt {attempt_i + 1} (restart budget "
                f"{restarts_used}/{p.max_restarts} used; resume step: "
                f"{step_before if step_before is not None else 'fresh'})."
            )
            self._ledger_event(
                "attempt_start", attempt=attempt_i, resume_step=step_before
            )
            self._child = self.launch(attempt_i)
            try:
                rc = self._wait(self._child)
            finally:
                self._child = None
            outcome = self._classify_outcome(rc)
            step_after = self.progress()
            self._ledger_event(
                "attempt_end", attempt=attempt_i, returncode=rc,
                outcome=outcome, step=step_after,
            )
            attempt = Attempt(attempt_i, rc, outcome, step_before, step_after)
            attempts.append(attempt)
            attempt_i += 1
            self._post_attempt(attempt)

            if outcome == CLEAN:
                logger.warning(
                    f"SUPERVISOR: clean exit after {len(attempts)} attempt(s) "
                    f"(final step: {step_after})."
                )
                persist(CLEAN)
                return SupervisorResult(CLEAN, attempts)

            if self._terminate_signum is not None:
                # the supervisor itself was told to stop; the child already
                # received the forwarded signal and has now exited — report
                # and stand down instead of restarting
                return terminated(step_after)

            if attempt.progressed:
                no_progress_streak = 0
            elif outcome in _COORDINATED:
                # a coordinated pod restart is not THIS host failing:
                # exempt from the budget AND the crash-loop streak — a
                # crash-looping peer is bounded by its OWN supervisor,
                # which aborts and publishes 'failed' (then HOST_LOST
                # shrinks the world here instead of looping forever)
                pass
            else:
                no_progress_streak += 1
                restarts_used += 1
            persist("running")
            logger.error(
                f"SUPERVISOR: attempt {attempt_i} exited {rc} "
                f"[{outcome}]; checkpoint step {step_before} -> {step_after} "
                f"({'progress' if attempt.progressed else 'NO progress'}, "
                f"streak {no_progress_streak}/{p.crash_loop_window})."
            )

            if no_progress_streak >= p.crash_loop_window:
                diagnosis = (
                    f"SUPERVISOR: crash-loop: no global_step progress across "
                    f"{no_progress_streak} consecutive failed attempts "
                    f"(last exit {rc} [{outcome}], stuck at step "
                    f"{step_after if step_after is not None else 'none'}); "
                    f"aborting — restarting further would burn the retry "
                    f"budget without converging."
                    + self._flight_timeline()
                )
                logger.error(diagnosis)
                sys.stderr.write(diagnosis + "\n")
                sys.stderr.flush()
                persist("crash-loop")
                return SupervisorResult("crash-loop", attempts, diagnosis)

            if restarts_used > p.max_restarts:
                break
            attempt.backoff = self._backoff(no_progress_streak)
            logger.warning(
                f"SUPERVISOR: restarting [{outcome}] in {attempt.backoff:.2f}s."
            )
            self.sleep(attempt.backoff)

        diagnosis = (
            f"SUPERVISOR: retry budget exhausted after "
            f"{len(attempts)} attempts (outcomes: "
            f"{', '.join(a.outcome for a in attempts)})."
            + self._flight_timeline()
        )
        logger.error(diagnosis)
        sys.stderr.write(diagnosis + "\n")
        sys.stderr.flush()
        persist("retries-exhausted")
        return SupervisorResult("retries-exhausted", attempts, diagnosis)


# -- elastic (cross-host) supervision ------------------------------------------


class ElasticSupervisor(Supervisor):
    """Cross-host elastic supervision (``--elastic on``).

    One ElasticSupervisor runs per host; they coordinate through per-host
    heartbeat files (:class:`~.coordination.PodCoordinator`) instead of a
    control channel. The base retry loop is unchanged — this subclass
    replaces the blocking child wait with a polling wait that, every
    ``poll_interval`` seconds:

    1. publishes this host's heartbeat (status, generation, attempt, the
       child's last reported step);
    2. reads every live peer's document: a peer at a HIGHER generation
       means the pod is restarting -> kill our (wedged) child now instead
       of letting it wait out the collective timeout; a peer whose
       heartbeat is stale past ``host_timeout`` (or that published status
       'failed' — its own supervisor gave up on a crash-loop) is declared
       LOST -> drop it from the live set, bump the generation and restart
       on the shrunk world.

    The launch callback reads :attr:`world` for the CURRENT live world
    (hosts, size, this host's rank, generation) so each attempt's child is
    told the topology it is actually joining; a shrunk child re-derives
    its mesh via ``ParallelPlan.elastic_from_spec``. When this host's own
    child fails, the generation is bumped BEFORE the backoff so every
    surviving peer restarts immediately. Host death vs crash-loop is
    classified explicitly: a self-reported 'failed' status is a peer
    crash-loop, a silent stale heartbeat is a dead host — both shrink the
    world, but the diagnosis (and the flight-recorder event) names which.
    """

    def __init__(
        self,
        launch: Callable[[int], object],
        *,
        coordinator: PodCoordinator,
        host_timeout: float = 60.0,
        poll_interval: float = 2.0,
        min_world: int = 1,
        kill_grace: float = 5.0,
        **kwargs,
    ):
        super().__init__(launch, **kwargs)
        self.coordinator = coordinator
        self.host_timeout = float(host_timeout)
        self.poll_interval = float(poll_interval)
        self.min_world = max(1, int(min_world))
        self.kill_grace = float(kill_grace)
        self.generation = 0
        self._attempt_i = 0
        self._dead_hosts: set = set()
        self._done_hosts: set = set()
        self._lost_why: dict = {}          # host -> classification text
        self._kill_reason = None           # (outcome, peer host) | None
        self._last_good: dict = {}         # host -> monotonic of last good read
        self._started = time.monotonic()
        self._flight = None
        if self.flight_dir is not None:
            from ..metrics.flightrec import FlightRecorder

            # the supervisor keeps its OWN bounded event ring: elastic
            # transitions (host_lost / pod_restart) land in a dump the
            # crash-loop diagnosis reads back, explaining topology changes
            self._flight = FlightRecorder.open_in(
                self.flight_dir, process_index=coordinator.host,
                capacity=64,
            )

    # -- live-world bookkeeping ------------------------------------------------

    def live_hosts(self) -> List[int]:
        return [
            h for h in range(self.coordinator.n_hosts)
            if h not in self._dead_hosts
        ]

    @property
    def world(self) -> dict:
        """The CURRENT live world, for the launch callback: surviving
        hosts in id order, the shrunk world size, this host's rank within
        it, and the pod generation."""
        live = self.live_hosts()
        return {
            "hosts": live,
            "size": len(live),
            "rank": live.index(self.coordinator.host),
            "generation": self.generation,
        }

    def _note_elastic(self, kind: str, **fields) -> None:
        """An elastic transition: goodput-ledger event + flight-recorder
        event (dumped immediately — transitions are rare and must survive
        whatever happens next)."""
        self._ledger_event(kind, host=self.coordinator.host, **fields)
        if self._flight is not None:
            self._flight.record(kind, **fields)
            self._flight.dump("elastic", transition=kind)

    def _heartbeat(self, status: str = "running") -> None:
        self.coordinator.publish(
            status,
            generation=self.generation,
            attempt=self._attempt_i,
            step=self.coordinator.child_step(self.coordinator.host),
            live_hosts=self.live_hosts(),
        )

    # -- peer policy -----------------------------------------------------------

    def _declare_host_lost(self, host: int, *, why: str):
        self._dead_hosts.add(host)
        self._lost_why[host] = why
        self.generation += 1
        last_step = self.coordinator.child_step(host)
        logger.error(
            f"SUPERVISOR[elastic h{self.coordinator.host}]: host {host} "
            f"LOST ({why}; last reported step "
            f"{last_step if last_step is not None else 'none'}); live "
            f"hosts now {self.live_hosts()}; restarting the pod at "
            f"generation {self.generation}."
        )
        self._note_elastic(
            "host_lost", lost=host, why=why, generation=self.generation,
            last_step=last_step, live_hosts=self.live_hosts(),
        )
        return (HOST_LOST, host)

    def _check_peers(self):
        """One coordination sweep. Returns ``(outcome, peer)`` when the
        live child must be killed for a coordinated restart, else None."""
        now = time.monotonic()
        from ..metrics.artifacts import wall_now

        for h in self.live_hosts():
            if h == self.coordinator.host or h in self._done_hosts:
                continue
            doc = self.coordinator.peer_state(h)
            if doc is not None:
                self._last_good[h] = now
                status = doc.get("status")
                if status == "done":
                    self._done_hosts.add(h)
                    continue
                if status == "failed":
                    # the peer's OWN supervisor gave up (crash-loop /
                    # retries-exhausted): a classified failure, not a
                    # silent death — but the pod shrinks either way
                    return self._declare_host_lost(
                        h, why="its supervisor reported 'failed' "
                               "(peer crash-loop)",
                    )
                gen = int(doc.get("generation", 0))
                if gen > self.generation:
                    self.generation = gen
                    logger.warning(
                        f"SUPERVISOR[elastic h{self.coordinator.host}]: "
                        f"host {h} published generation {gen}; joining the "
                        f"pod restart."
                    )
                    self._note_elastic(
                        "pod_restart", origin=h, generation=gen,
                    )
                    return (POD_RESTART, h)
                # heartbeat age from the WALL stamp (hosts are NTP-synced
                # at coarse, multi-second granularity): catches a dead
                # supervisor whose file corpse remains readable
                age = wall_now() - float(doc.get("heartbeat", 0.0))
            else:
                # unreadable/absent even after the bounded retry: age from
                # the last GOOD read (never from one torn read — that is
                # the misclassification the retry exists to prevent)
                age = now - self._last_good.get(h, self._started)
            if age > self.host_timeout:
                return self._declare_host_lost(
                    h, why=f"heartbeat stale for {age:.1f}s "
                           f"(> {self.host_timeout:g}s; host death)",
                )
        return None

    # -- overridden loop pieces ------------------------------------------------

    def _pre_attempt(self, attempt_i: int):
        self._attempt_i = attempt_i
        live = self.live_hosts()
        if len(live) < self.min_world:
            detail = "; ".join(
                f"host {h}: {why}" for h, why in sorted(self._lost_why.items())
            )
            return (
                "world-floor",
                f"SUPERVISOR[elastic h{self.coordinator.host}]: only "
                f"{len(live)} live host(s) remain ({detail}) — below the "
                f"--min_world floor of {self.min_world}; aborting instead "
                f"of training degenerately narrow." + self._flight_timeline(),
            )
        if 0 in self._dead_hosts and len(live) > 1:
            detail = self._lost_why.get(0, "lost")
            return (
                "coordinator-lost",
                f"SUPERVISOR[elastic h{self.coordinator.host}]: host 0 was "
                f"lost ({detail}) and {len(live)} hosts remain — the "
                f"rendezvous coordinator address lives on host 0, so the "
                f"shrunk pod cannot re-form; aborting. (A single surviving "
                f"host would have continued solo.)" + self._flight_timeline(),
            )
        self._heartbeat("running")
        return None

    def _wait(self, child) -> int:
        if isinstance(child, int):
            # scripted attempts (unit tests): still run one coordination
            # sweep so peer-driven outcomes are drivable without a process
            self._kill_reason = self._check_peers()
            return child
        self._kill_reason = None
        start = time.monotonic()
        while True:
            timeout = self.poll_interval
            if self.attempt_timeout is not None:
                remaining = self.attempt_timeout - (time.monotonic() - start)
                if remaining <= 0:
                    logger.error(
                        f"Attempt exceeded the {self.attempt_timeout:g}s "
                        f"wall clock; killing the child."
                    )
                    child.kill()
                    child.wait()
                    return WATCHDOG_EXIT_CODE
                timeout = min(timeout, remaining)
            try:
                return child.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                pass
            if self._terminate_signum is not None:
                # operator shutdown: the signal was already forwarded to
                # the child; keep waiting for it to unwind (no peer logic)
                continue
            self._heartbeat("running")
            reason = self._check_peers()
            if reason is not None:
                self._kill_reason = reason
                return self._stop_child(child)

    def _stop_child(self, child) -> int:
        """Coordinated kill: SIGTERM first (the child's interrupt-
        checkpoint path gets ``kill_grace`` seconds to save), then
        SIGKILL. The collective the child is wedged in never returns on
        its own — that is the whole point of killing it."""
        try:
            child.terminate()
        except OSError:
            pass
        try:
            return child.wait(timeout=self.kill_grace)
        except subprocess.TimeoutExpired:
            child.kill()
            return child.wait()

    def _classify_outcome(self, rc: int) -> str:
        if self._kill_reason is not None:
            outcome, _peer = self._kill_reason
            return outcome
        return classify_exit(rc)

    def _post_attempt(self, attempt: Attempt) -> None:
        if attempt.outcome == CLEAN:
            self._heartbeat("done")
        elif attempt.outcome in _COORDINATED:
            # generation already adopted/bumped by the sweep that killed
            # the child; just make the restart visible to peers
            self._heartbeat("restarting")
        else:
            # this host's OWN child failed (crash/hang/preempt): peers'
            # children are wedged in collectives waiting for us — bump the
            # generation so every surviving supervisor restarts NOW
            # instead of waiting out the rendezvous/collective timeout
            self.generation += 1
            self._note_elastic(
                "pod_restart", origin=self.coordinator.host,
                generation=self.generation, returncode=attempt.returncode,
                outcome=attempt.outcome,
            )
            self._heartbeat("restarting")

    def _persist_state(self, status, attempts, **kwargs) -> None:
        super()._persist_state(status, attempts, **kwargs)
        # terminal supervisor states double as coordination signals: a
        # peer that reads 'failed' classifies us as a crash-loop (not a
        # host death) and shrinks the pod without waiting for staleness
        if status in ("crash-loop", "retries-exhausted", "terminated",
                      "world-floor", "coordinator-lost"):
            self._heartbeat("failed")
        elif status == CLEAN:
            self._heartbeat("done")


# -- checkpoint progress probing ----------------------------------------------


def newest_checkpoint(candidates: Sequence, *, retries: int = 0) -> tuple:
    """``(path, step)`` of the candidate with the highest peekable
    ``global_step`` (``(None, None)`` when none is loadable). Imports the
    checkpoint module lazily: the supervisor itself must not pay (or
    depend on) the jax import. ``retries`` re-probes an unreadable
    candidate (elastic supervisors probe checkpoints a PEER may be
    mid-swap on; a fixed-world supervisor only reads its own)."""
    from ..train.checkpoint import peek_global_step

    best, best_step = None, None
    for cand in candidates:
        step = peek_global_step(cand, retries=retries)
        if step is not None and (best_step is None or step > best_step):
            best, best_step = cand, step
    return best, best_step


# -- CLI wiring ----------------------------------------------------------------

# Set in every supervised child: (a) lets the train CLI turn a caught
# preemption into PREEMPT_EXIT_CODE, (b) breaks --supervise recursion even
# when the flag comes from a config file the child re-reads.
SUPERVISED_ENV = "MLRT_SUPERVISED"


def build_child_argv(
    argv: Sequence[str], *, resume: Optional[str] = None
) -> List[str]:
    """Strip supervisor-only flags from ``argv`` and re-point ``--last``."""
    out: List[str] = []
    skip_value = False
    for arg in argv:
        if skip_value:
            skip_value = False
            continue
        if arg == "--supervise" or arg.startswith("--supervise="):
            continue
        if resume is not None:
            if arg == "--last":
                skip_value = True
                continue
            if arg.startswith("--last="):
                continue
        out.append(arg)
    if resume is not None:
        out.extend(["--last", resume])
    return out


def _policy_from_params(params) -> RetryPolicy:
    return RetryPolicy(
        max_restarts=getattr(params, "max_restarts", 5),
        backoff_base=getattr(params, "backoff_base", 1.0),
        backoff_max=getattr(params, "backoff_max", 30.0),
        crash_loop_window=getattr(params, "crash_loop_window", 3),
        seed=getattr(params, "seed", None) or 0,
    )


def supervise_cli(params, argv: Sequence[str]) -> int:
    """Drive ``python -m ml_recipe_tpu.cli.train`` under supervision.

    Resumes each attempt from the newest of ``interrupt.ch`` / ``last.ch``
    in the experiment directory (emergency checkpoints win when they are
    ahead, which they are after a mid-epoch preemption). With
    ``--elastic on`` this becomes one host's member of a coordinated pod
    (see :class:`ElasticSupervisor`); the default path is byte-identical
    to fixed-world supervision and never touches the coordination dir.
    """
    exp_dir = os.path.join(os.fspath(params.dump_dir), params.experiment_name)
    candidates = [
        os.path.join(exp_dir, "interrupt.ch"),
        os.path.join(exp_dir, "last.ch"),
    ]
    if getattr(params, "elastic", "off") != "off":
        return _supervise_elastic(params, argv, exp_dir, candidates)

    def progress() -> Optional[int]:
        return newest_checkpoint(candidates)[1]

    def launch(attempt_i: int):
        resume, step = newest_checkpoint(candidates)
        child_argv = build_child_argv(argv, resume=resume)
        env = dict(os.environ)
        env[SUPERVISED_ENV] = "1"
        logger.warning(
            f"SUPERVISOR: launching attempt {attempt_i + 1}"
            + (f" resuming {resume} (step {step})" if resume else " fresh")
            + "."
        )
        return subprocess.Popen(
            [sys.executable, "-m", "ml_recipe_tpu.cli.train", *child_argv],
            env=env,
        )

    from ..metrics.goodput import GOODPUT_FILENAME

    result = Supervisor(
        launch, progress=progress, policy=_policy_from_params(params),
        state_path=os.path.join(exp_dir, STATE_FILENAME),
        # attempt boundaries land in the same ledger the child feeds, so
        # restart downtime is partitioned out of the run wall-clock
        ledger_path=(
            os.path.join(exp_dir, GOODPUT_FILENAME)
            if getattr(params, "goodput_ledger", False) else None
        ),
        # crash-loop diagnoses read the newest flight-record dump back
        flight_dir=(
            exp_dir if getattr(params, "flight_recorder", False) else None
        ),
    ).run()
    return result.exit_code


def _supervise_elastic(
    params, argv: Sequence[str], exp_dir: str, candidates: Sequence[str]
) -> int:
    """One host's member of the coordinated elastic pod (``--elastic on``).

    Differences from fixed-world supervision, and nothing else:

    - a :class:`~.coordination.PodCoordinator` under ``<exp_dir>/pod/``
      publishes this host's heartbeat and reads the peers';
    - every child is launched with ``MLRT_HOST`` (host-scoped fault specs)
      and ``MLRT_ELASTIC_WORLD=<size>:<rank>`` for the CURRENT live world,
      so after a host loss the survivors re-form a smaller pod and the
      trainer re-derives its mesh from the devices actually present;
    - checkpoint probes retry a couple of times: a PEER host may be
      mid-swap on the shared checkpoint this host is peeking at;
    - only host 0 appends supervisor events to the goodput ledger (same
      process-0-only discipline as the training-side ledger writer), and
      each host keeps its own sidecar (host 0 owns the canonical name).
    """
    host = max(int(getattr(params, "local_rank", 0) or 0), 0)
    n_hosts = max(int(getattr(params, "dist_world_size", 1) or 1), 1)
    coordinator = PodCoordinator(
        os.path.join(exp_dir, COORD_DIRNAME), host=host, n_hosts=n_hosts
    )

    def progress() -> Optional[int]:
        return newest_checkpoint(candidates, retries=2)[1]

    sup_holder: List[ElasticSupervisor] = []

    def launch(attempt_i: int):
        world = sup_holder[0].world
        resume, step = newest_checkpoint(candidates, retries=2)
        child_argv = build_child_argv(argv, resume=resume)
        env = dict(os.environ)
        env[SUPERVISED_ENV] = "1"
        env[HOST_ENV] = str(host)
        env[ELASTIC_WORLD_ENV] = f"{world['size']}:{world['rank']}"
        logger.warning(
            f"SUPERVISOR[elastic h{host}]: launching attempt {attempt_i + 1} "
            f"generation {world['generation']} as rank {world['rank']}/"
            f"{world['size']} (live hosts {world['hosts']})"
            + (f", resuming {resume} (step {step})" if resume else ", fresh")
            + "."
        )
        return subprocess.Popen(
            [sys.executable, "-m", "ml_recipe_tpu.cli.train", *child_argv],
            env=env,
        )

    from ..metrics.goodput import GOODPUT_FILENAME

    state_name = (
        STATE_FILENAME if host == 0 else f"supervisor_state_h{host}.json"
    )
    sup = ElasticSupervisor(
        launch,
        coordinator=coordinator,
        host_timeout=getattr(params, "host_timeout", 60.0),
        poll_interval=getattr(params, "coord_poll", 2.0),
        min_world=getattr(params, "min_world", 1),
        progress=progress,
        policy=_policy_from_params(params),
        state_path=os.path.join(exp_dir, state_name),
        ledger_path=(
            os.path.join(exp_dir, GOODPUT_FILENAME)
            if host == 0 and getattr(params, "goodput_ledger", False)
            else None
        ),
        flight_dir=(
            exp_dir if getattr(params, "flight_recorder", False) else None
        ),
    )
    sup_holder.append(sup)
    return sup.run().exit_code
