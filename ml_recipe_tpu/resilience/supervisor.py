"""Auto-resume supervisor: the local analogue of an elastic agent.

Wraps the training entrypoint in a bounded-retry loop (``--supervise`` on
the train CLI). Each attempt is a child process; on exit the supervisor

1. classifies the exit — clean / preempted / hang (watchdog abort) /
   crash — from the return code,
2. measures progress by peeking ``global_step`` out of the newest on-disk
   checkpoint (no cooperation from the child needed: a hard-killed child
   reports through what it durably saved, which is the only truth anyway),
3. restarts with ``--last <newest checkpoint>`` after an exponential
   backoff with seeded jitter (deterministic: drills replay identically),
4. aborts with a diagnosis once ``crash_loop_window`` consecutive failed
   attempts made NO checkpoint progress — a crash-loop restarted forever
   is strictly worse than a loud early exit with the failure classified.

The supervisor deliberately knows nothing about JAX: it manages a process
and a checkpoint directory. That is what lets the chaos suite drive real
kill/stall scenarios through it in milliseconds-per-decision on CPU.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import signal
import subprocess
import sys
import time
from datetime import datetime, timezone
from typing import Callable, List, Optional, Sequence

from .watchdog import WATCHDOG_EXIT_CODE

logger = logging.getLogger(__name__)

# JSON sidecar the supervisor keeps current next to the checkpoints, so the
# training exporter (and humans) read restart counts / exit classifications
# / backoff state without parsing logs. Written atomically (tmp + rename):
# a reader never sees a torn document.
STATE_FILENAME = "supervisor_state.json"


def write_supervisor_state(path, state: dict) -> None:
    """Atomically persist the supervisor's observable state."""
    path = os.fspath(path)
    directory = os.path.dirname(path)
    if directory:
        os.makedirs(directory, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(state, fh, indent=2)
    os.replace(tmp, path)


def peek_supervisor_state(path) -> Optional[dict]:
    """Best-effort read of the sidecar; None when absent or unreadable
    (an exporter scrape must never crash on a mid-replace race or a
    corrupt file)."""
    try:
        with open(os.fspath(path)) as fh:
            state = json.load(fh)
    except (OSError, ValueError):
        return None
    return state if isinstance(state, dict) else None

# A supervised child that caught SIGTERM/SIGINT, saved interrupt.ch and
# unwound cleanly exits with this (EX_TEMPFAIL) instead of 0, so the
# supervisor restarts it — a preemption is a reason to resume, not to stop.
PREEMPT_EXIT_CODE = 75

CLEAN = "clean"
PREEMPTED = "preempted"
HANG = "hang"
CRASH = "crash"

# exits worth retrying; CLEAN ends the loop, anything unknown is a crash
_RETRYABLE = (PREEMPTED, HANG, CRASH)


def classify_exit(returncode: int) -> str:
    """Map a child return code onto an exit class."""
    if returncode == 0:
        return CLEAN
    if returncode == WATCHDOG_EXIT_CODE:
        return HANG
    if returncode == PREEMPT_EXIT_CODE:
        return PREEMPTED
    # Popen reports death-by-signal as -signum; platform evictions that
    # skip our SIGTERM hook surface as SIGKILL/SIGTERM here. 128+signum
    # covers shells that re-encode it. An injected drill kill
    # (KILL_EXIT_CODE) stays a crash: mid-write kills are the scenario
    # being tested, not an infra event to blame.
    for sig in (signal.SIGTERM, signal.SIGKILL, signal.SIGHUP):
        if returncode in (-int(sig), 128 + int(sig)):
            return PREEMPTED
    return CRASH


@dataclasses.dataclass
class RetryPolicy:
    # Restarts chargeable AFTER the first attempt. Only failures WITHOUT
    # checkpoint progress consume the budget: on preemptible pools a
    # healthy multi-day run is preempted far more than any fixed budget,
    # and a preemption that resumed and advanced global_step is the system
    # WORKING, not failing. Pathological progress-making crash cycles are
    # still bounded by the crash-loop detector the moment progress stops.
    max_restarts: int = 5
    backoff_base: float = 1.0      # seconds before restart #1
    backoff_factor: float = 2.0
    backoff_max: float = 30.0
    jitter: float = 0.1            # +-10% seeded jitter (thundering herd)
    crash_loop_window: int = 3     # consecutive no-progress failures -> abort
    seed: int = 0


@dataclasses.dataclass
class Attempt:
    index: int
    returncode: int
    outcome: str
    step_before: Optional[int]
    step_after: Optional[int]
    backoff: float = 0.0           # sleep AFTER this attempt (0 = none)

    @property
    def progressed(self) -> bool:
        if self.step_after is None:
            return False
        return self.step_before is None or self.step_after > self.step_before


@dataclasses.dataclass
class SupervisorResult:
    status: str        # 'clean' | 'crash-loop' | 'retries-exhausted' | 'terminated'
    attempts: List[Attempt]
    diagnosis: str = ""
    signum: Optional[int] = None   # set when status == 'terminated'

    @property
    def exit_code(self) -> int:
        if self.signum is not None:
            return 128 + int(self.signum)  # shell convention: died by signal
        return {"clean": 0, "crash-loop": 1}.get(self.status, 2)

    def outcomes(self) -> List[str]:
        return [a.outcome for a in self.attempts]


class Supervisor:
    """Bounded-retry loop around a launchable child.

    ``launch(attempt_index)`` returns either a ``Popen``-like object (with
    ``wait``/``kill``) or a bare int return code (tests). ``progress()``
    returns the newest durable ``global_step`` (or None) — called before
    and after every attempt. ``sleep`` is injectable so drills don't
    actually wait out the backoff.
    """

    def __init__(
        self,
        launch: Callable[[int], object],
        *,
        progress: Callable[[], Optional[int]],
        policy: Optional[RetryPolicy] = None,
        attempt_timeout: Optional[float] = None,
        sleep: Callable[[float], None] = time.sleep,
        state_path=None,
        ledger_path=None,
        flight_dir=None,
    ):
        self.launch = launch
        self.progress = progress
        self.policy = policy or RetryPolicy()
        self.attempt_timeout = attempt_timeout
        self.sleep = sleep
        self.state_path = os.fspath(state_path) if state_path else None
        # goodput ledger (metrics/goodput.py): attempt boundaries appended
        # here partition restart downtime out of the run's wall-clock
        self.ledger_path = os.fspath(ledger_path) if ledger_path else None
        # flight-recorder dumps (metrics/flightrec.py) live here; the exit
        # classifier reads the newest one back into its diagnoses
        self.flight_dir = os.fspath(flight_dir) if flight_dir else None
        self._rng = random.Random(self.policy.seed)
        self._child = None
        self._terminate_signum: Optional[int] = None

    def _ledger_event(self, ev: str, **fields) -> None:
        """Append an attempt-boundary event to the goodput ledger; a
        failure degrades accounting, never supervision (same contract as
        the sidecar)."""
        if self.ledger_path is None:
            return
        from ..metrics.goodput import append_event

        try:
            append_event(self.ledger_path, ev, pid=os.getpid(), **fields)
        except OSError as e:
            logger.warning(
                f"SUPERVISOR: could not append {ev} to the goodput ledger "
                f"{self.ledger_path}: {e}"
            )

    def _flight_timeline(self) -> str:
        """The newest flight-record dump's last-K-step timeline, rendered
        for a diagnosis ('' when no recorder ran or nothing is readable)."""
        if self.flight_dir is None:
            return ""
        from ..metrics.flightrec import newest_flight_record, timeline_lines

        found = newest_flight_record(self.flight_dir)
        if found is None:
            return ""
        path, doc = found
        lines = timeline_lines(doc, last=8)
        if not lines:
            return ""
        return (
            f"\nFlight recorder ({os.path.basename(path)}, dumped on "
            f"{doc.get('reason', '?')}): last {len(lines)} event(s):\n"
            + "\n".join(lines)
        )

    def _persist_state(
        self,
        status: str,
        attempts: List["Attempt"],
        *,
        restarts_used: int = 0,
        no_progress_streak: int = 0,
    ) -> None:
        """Keep the JSON sidecar current; failures degrade observability,
        never the supervision loop itself."""
        if self.state_path is None:
            return
        last = attempts[-1] if attempts else None
        state = {
            "pid": os.getpid(),
            "status": status,
            "attempts": len(attempts),
            "restarts_used": restarts_used,
            "max_restarts": self.policy.max_restarts,
            "no_progress_streak": no_progress_streak,
            "crash_loop_window": self.policy.crash_loop_window,
            "outcomes": [a.outcome for a in attempts],
            "last_returncode": last.returncode if last else None,
            "last_outcome": last.outcome if last else None,
            "step": last.step_after if last else None,
            "last_backoff_s": last.backoff if last else 0.0,
            # wall-clock EVENT stamp (not an interval measurement): humans
            # and dashboards correlate this with logs and scrape times
            "updated_at": datetime.now(timezone.utc).isoformat(),
        }
        try:
            write_supervisor_state(self.state_path, state)
        except OSError as e:
            logger.warning(
                f"SUPERVISOR: could not persist state to "
                f"{self.state_path}: {e}"
            )

    # -- supervisor-level signals ----------------------------------------------

    def _forward_signal(self, signum, frame) -> None:
        """SIGTERM/SIGINT on the SUPERVISOR: forward to the live child (so
        it takes its own save-and-exit path) and stop supervising after it
        exits — never orphan a training process that would race the next
        submission's child on the checkpoint directory."""
        self._terminate_signum = int(signum)
        child = self._child
        if child is not None and hasattr(child, "send_signal"):
            try:
                child.send_signal(signum)
            except OSError:  # child already gone
                pass

    def _install_signal_handlers(self):
        import threading

        if threading.current_thread() is not threading.main_thread():
            return None  # signal.signal raises off the main thread
        prev = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            prev[sig] = signal.signal(sig, self._forward_signal)
        return prev

    # -- one attempt -----------------------------------------------------------

    def _wait(self, child) -> int:
        if isinstance(child, int):
            return child
        try:
            return child.wait(timeout=self.attempt_timeout)
        except subprocess.TimeoutExpired:
            # supervisor-side wall clock tripped: the child has no (working)
            # watchdog — kill it and classify as a hang ourselves
            logger.error(
                f"Attempt exceeded the {self.attempt_timeout:g}s wall clock; "
                f"killing the child."
            )
            child.kill()
            child.wait()
            return WATCHDOG_EXIT_CODE

    def _backoff(self, no_progress_streak: int) -> float:
        """Backoff grows with CONSECUTIVE no-progress failures (a persistent
        fault deserves widening gaps); a restart after a progressing
        failure — a resumed preemption — waits only the base."""
        p = self.policy
        base = min(
            p.backoff_base * (p.backoff_factor ** max(no_progress_streak - 1, 0)),
            p.backoff_max,
        )
        return base * (1.0 + p.jitter * self._rng.uniform(-1.0, 1.0))

    # -- the loop --------------------------------------------------------------

    def run(self) -> SupervisorResult:
        prev_handlers = self._install_signal_handlers()
        try:
            return self._run()
        finally:
            if prev_handlers:
                for sig, handler in prev_handlers.items():
                    signal.signal(sig, handler)

    def _run(self) -> SupervisorResult:
        p = self.policy
        attempts: List[Attempt] = []
        no_progress_streak = 0
        restarts_used = 0  # only no-progress failures consume the budget

        def persist(status: str) -> None:
            self._persist_state(
                status, attempts,
                restarts_used=restarts_used,
                no_progress_streak=no_progress_streak,
            )

        def terminated(step) -> SupervisorResult:
            diagnosis = (
                f"SUPERVISOR: terminated by signal {self._terminate_signum} "
                f"(checkpoint step {step}); standing down without restart."
            )
            logger.error(diagnosis)
            sys.stderr.write(diagnosis + "\n")
            sys.stderr.flush()
            persist("terminated")
            return SupervisorResult(
                "terminated", attempts, diagnosis, signum=self._terminate_signum
            )

        persist("running")
        attempt_i = 0
        while True:
            step_before = self.progress()
            if self._terminate_signum is not None:
                # signal arrived between attempts (e.g. during backoff):
                # do not launch another child
                return terminated(step_before)
            logger.warning(
                f"SUPERVISOR: attempt {attempt_i + 1} (restart budget "
                f"{restarts_used}/{p.max_restarts} used; resume step: "
                f"{step_before if step_before is not None else 'fresh'})."
            )
            self._ledger_event(
                "attempt_start", attempt=attempt_i, resume_step=step_before
            )
            self._child = self.launch(attempt_i)
            try:
                rc = self._wait(self._child)
            finally:
                self._child = None
            outcome = classify_exit(rc)
            step_after = self.progress()
            self._ledger_event(
                "attempt_end", attempt=attempt_i, returncode=rc,
                outcome=outcome, step=step_after,
            )
            attempt = Attempt(attempt_i, rc, outcome, step_before, step_after)
            attempts.append(attempt)
            attempt_i += 1

            if outcome == CLEAN:
                logger.warning(
                    f"SUPERVISOR: clean exit after {len(attempts)} attempt(s) "
                    f"(final step: {step_after})."
                )
                persist(CLEAN)
                return SupervisorResult(CLEAN, attempts)

            if self._terminate_signum is not None:
                # the supervisor itself was told to stop; the child already
                # received the forwarded signal and has now exited — report
                # and stand down instead of restarting
                return terminated(step_after)

            if attempt.progressed:
                no_progress_streak = 0
            else:
                no_progress_streak += 1
                restarts_used += 1
            persist("running")
            logger.error(
                f"SUPERVISOR: attempt {attempt_i} exited {rc} "
                f"[{outcome}]; checkpoint step {step_before} -> {step_after} "
                f"({'progress' if attempt.progressed else 'NO progress'}, "
                f"streak {no_progress_streak}/{p.crash_loop_window})."
            )

            if no_progress_streak >= p.crash_loop_window:
                diagnosis = (
                    f"SUPERVISOR: crash-loop: no global_step progress across "
                    f"{no_progress_streak} consecutive failed attempts "
                    f"(last exit {rc} [{outcome}], stuck at step "
                    f"{step_after if step_after is not None else 'none'}); "
                    f"aborting — restarting further would burn the retry "
                    f"budget without converging."
                    + self._flight_timeline()
                )
                logger.error(diagnosis)
                sys.stderr.write(diagnosis + "\n")
                sys.stderr.flush()
                persist("crash-loop")
                return SupervisorResult("crash-loop", attempts, diagnosis)

            if restarts_used > p.max_restarts:
                break
            attempt.backoff = self._backoff(no_progress_streak)
            logger.warning(
                f"SUPERVISOR: restarting [{outcome}] in {attempt.backoff:.2f}s."
            )
            self.sleep(attempt.backoff)

        diagnosis = (
            f"SUPERVISOR: retry budget exhausted after "
            f"{len(attempts)} attempts (outcomes: "
            f"{', '.join(a.outcome for a in attempts)})."
            + self._flight_timeline()
        )
        logger.error(diagnosis)
        sys.stderr.write(diagnosis + "\n")
        sys.stderr.flush()
        persist("retries-exhausted")
        return SupervisorResult("retries-exhausted", attempts, diagnosis)


# -- checkpoint progress probing ----------------------------------------------


def newest_checkpoint(candidates: Sequence) -> tuple:
    """``(path, step)`` of the candidate with the highest peekable
    ``global_step`` (``(None, None)`` when none is loadable). Imports the
    checkpoint module lazily: the supervisor itself must not pay (or
    depend on) the jax import."""
    from ..train.checkpoint import peek_global_step

    best, best_step = None, None
    for cand in candidates:
        step = peek_global_step(cand)
        if step is not None and (best_step is None or step > best_step):
            best, best_step = cand, step
    return best, best_step


# -- CLI wiring ----------------------------------------------------------------

# Set in every supervised child: (a) lets the train CLI turn a caught
# preemption into PREEMPT_EXIT_CODE, (b) breaks --supervise recursion even
# when the flag comes from a config file the child re-reads.
SUPERVISED_ENV = "MLRT_SUPERVISED"


def build_child_argv(
    argv: Sequence[str], *, resume: Optional[str] = None
) -> List[str]:
    """Strip supervisor-only flags from ``argv`` and re-point ``--last``."""
    out: List[str] = []
    skip_value = False
    for arg in argv:
        if skip_value:
            skip_value = False
            continue
        if arg == "--supervise" or arg.startswith("--supervise="):
            continue
        if resume is not None:
            if arg == "--last":
                skip_value = True
                continue
            if arg.startswith("--last="):
                continue
        out.append(arg)
    if resume is not None:
        out.extend(["--last", resume])
    return out


def supervise_cli(params, argv: Sequence[str]) -> int:
    """Drive ``python -m ml_recipe_tpu.cli.train`` under supervision.

    Resumes each attempt from the newest of ``interrupt.ch`` / ``last.ch``
    in the experiment directory (emergency checkpoints win when they are
    ahead, which they are after a mid-epoch preemption).
    """
    exp_dir = os.path.join(os.fspath(params.dump_dir), params.experiment_name)
    candidates = [
        os.path.join(exp_dir, "interrupt.ch"),
        os.path.join(exp_dir, "last.ch"),
    ]

    def progress() -> Optional[int]:
        return newest_checkpoint(candidates)[1]

    def launch(attempt_i: int):
        resume, step = newest_checkpoint(candidates)
        child_argv = build_child_argv(argv, resume=resume)
        env = dict(os.environ)
        env[SUPERVISED_ENV] = "1"
        logger.warning(
            f"SUPERVISOR: launching attempt {attempt_i + 1}"
            + (f" resuming {resume} (step {step})" if resume else " fresh")
            + "."
        )
        return subprocess.Popen(
            [sys.executable, "-m", "ml_recipe_tpu.cli.train", *child_argv],
            env=env,
        )

    policy = RetryPolicy(
        max_restarts=getattr(params, "max_restarts", 5),
        backoff_base=getattr(params, "backoff_base", 1.0),
        backoff_max=getattr(params, "backoff_max", 30.0),
        crash_loop_window=getattr(params, "crash_loop_window", 3),
        seed=getattr(params, "seed", None) or 0,
    )
    from ..metrics.goodput import GOODPUT_FILENAME

    result = Supervisor(
        launch, progress=progress, policy=policy,
        state_path=os.path.join(exp_dir, STATE_FILENAME),
        # attempt boundaries land in the same ledger the child feeds, so
        # restart downtime is partitioned out of the run wall-clock
        ledger_path=(
            os.path.join(exp_dir, GOODPUT_FILENAME)
            if getattr(params, "goodput_ledger", False) else None
        ),
        # crash-loop diagnoses read the newest flight-record dump back
        flight_dir=(
            exp_dir if getattr(params, "flight_recorder", False) else None
        ),
    ).run()
    return result.exit_code
