"""Fault-tolerance subsystem: supervised restart, step watchdog, fault drills.

Production multi-node training stands on three cooperating layers
(TorchTitan, arXiv:2410.06511; TPUv4 pjit ops report, arXiv:2204.06514 —
preemption is the steady state at pod scale, not the exception):

- :mod:`.supervisor` — wraps the training entrypoint in a bounded-retry
  loop: classifies exits (clean / preempted / hang / crash), auto-resumes
  from the newest valid checkpoint, detects crash-loops (no ``global_step``
  progress across K attempts) and aborts with a diagnosis instead of
  burning the retry budget.
- :mod:`.watchdog` — a heartbeat thread armed around every train/eval step
  and checkpoint barrier; a missed deadline (hung collective, stuck host)
  dumps all-thread stacks and aborts the process with a distinct exit code
  so the supervisor restarts instead of wedging the pod.
- :mod:`.faults` — a deterministic, env/config-driven fault-injection
  registry with named sites threaded through the checkpoint writer, the
  data loaders, and the distributed barriers, so every recovery path is
  testable (and drillable in production) under ``JAX_PLATFORMS=cpu``.

The passive pieces (atomic/sharded checkpoints with torn-save recovery,
SIGTERM-to-checkpoint) live in :mod:`..train.checkpoint` and
:mod:`..cli.train`; this package is the active layer that detects failure,
restarts, and proves the recovery paths work.
"""

from .coordination import (
    COORD_DIRNAME,
    COORD_SCHEMA_VERSION,
    ELASTIC_WORLD_ENV,
    CoordinationSchemaError,
    PodCoordinator,
    read_coordination_json,
    write_child_heartbeat,
)
from .faults import HOST_ENV, FaultError, FaultPlan, current_host, fire, install_plan
from .supervisor import (
    HOST_LOST,
    POD_RESTART,
    PREEMPT_EXIT_CODE,
    STATE_FILENAME,
    Attempt,
    ElasticSupervisor,
    RetryPolicy,
    Supervisor,
    SupervisorResult,
    classify_exit,
    peek_supervisor_state,
    write_supervisor_state,
)
from .watchdog import WATCHDOG_EXIT_CODE, Watchdog

__all__ = [
    "Attempt",
    "COORD_DIRNAME",
    "COORD_SCHEMA_VERSION",
    "CoordinationSchemaError",
    "ELASTIC_WORLD_ENV",
    "ElasticSupervisor",
    "FaultError",
    "FaultPlan",
    "HOST_ENV",
    "HOST_LOST",
    "POD_RESTART",
    "PREEMPT_EXIT_CODE",
    "PodCoordinator",
    "RetryPolicy",
    "STATE_FILENAME",
    "Supervisor",
    "SupervisorResult",
    "WATCHDOG_EXIT_CODE",
    "Watchdog",
    "classify_exit",
    "current_host",
    "fire",
    "install_plan",
    "peek_supervisor_state",
    "read_coordination_json",
    "write_child_heartbeat",
    "write_supervisor_state",
]
