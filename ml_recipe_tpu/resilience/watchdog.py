"""Step watchdog: turn silent hangs into supervised restarts.

A hung collective (one host dropped out of a barrier), a wedged data worker
or a stuck device transfer does not crash — it WAITS, forever, holding the
whole pod. The watchdog arms a deadline around every unit of work that must
make progress (train/eval steps, checkpoint barriers); when a deadline is
missed it dumps every thread's stack plus the last completed step to stderr
and aborts the process with :data:`WATCHDOG_EXIT_CODE`, so the supervisor
sees a classifiable exit (`hang`) and restarts from the last checkpoint
instead of wedging.

Arming is re-entrant (a stack): the trainer arms a step-level frame and the
checkpoint barrier arms its own nested frame on top; only the TOP frame's
deadline is monitored — it is the unit of work actually executing — and
when it pops, the frame below gets a fresh deadline (it just regained
control, so its clock restarts).

The monitor is a daemon thread, and the stack dump tries ``faulthandler``
first (works even when the main thread is stuck inside a C call that holds
the GIL) with a pure-Python fallback for captured/non-fd stderr.
"""

from __future__ import annotations

import faulthandler
import logging
import sys
import threading
import time
import traceback
from contextlib import contextmanager
from typing import Callable, List, Optional

logger = logging.getLogger(__name__)

# Distinct from any plausible library exit and from faults.KILL_EXIT_CODE:
# the supervisor classifies this as a hang.
WATCHDOG_EXIT_CODE = 87


class _Frame:
    __slots__ = ("label", "timeout", "deadline")

    def __init__(self, label: str, timeout: float):
        self.label = label
        self.timeout = timeout
        self.deadline = time.monotonic() + timeout


def dump_all_stacks(out) -> None:
    """Write every thread's stack to ``out`` (faulthandler when possible —
    it needs a real fd but works under a held GIL; python fallback keeps
    captured-stderr environments like pytest working)."""
    try:
        faulthandler.dump_traceback(file=out, all_threads=True)
        return
    except Exception:  # noqa: BLE001 - no fd / closed file: fall through
        pass
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in sys._current_frames().items():
        out.write(f"\n--- thread {names.get(tid, '?')} ({tid}) ---\n")
        out.write("".join(traceback.format_stack(frame)))


class Watchdog:
    """Deadline monitor for units of work that must make progress.

    Usage::

        wd = Watchdog(timeout=300)
        with wd.watch("train epoch 1") as tick:
            for step, batch in enumerate(loader):
                tick(f"train step {step}")   # fresh deadline per step
                ...
                wd.note_progress(step)

    ``on_timeout``/``exit_fn`` exist for tests; production uses the
    defaults (dump stacks, ``os._exit(WATCHDOG_EXIT_CODE)``).
    """

    def __init__(
        self,
        timeout: float,
        *,
        exit_code: int = WATCHDOG_EXIT_CODE,
        poll_interval: Optional[float] = None,
        on_timeout: Optional[Callable[[str], None]] = None,
        exit_fn: Optional[Callable[[int], None]] = None,
    ):
        if timeout <= 0:
            raise ValueError(f"watchdog timeout must be positive, got {timeout}")
        self.timeout = float(timeout)
        self.exit_code = exit_code
        self.poll_interval = (
            poll_interval if poll_interval is not None
            else max(0.02, min(1.0, self.timeout / 10.0))
        )
        self.on_timeout = on_timeout
        self._exit = exit_fn if exit_fn is not None else self._default_exit
        self._lock = threading.Lock()
        self._frames: List[_Frame] = []
        self._last_step: Optional[int] = None
        self._last_beat: Optional[float] = None  # monotonic, see heartbeat_age
        self._fired = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._on_beat: Optional[Callable[[Optional[int]], None]] = None
        self._on_beat_interval = 1.0
        self._beat_emitted: Optional[float] = None  # monotonic of last emit

    # -- arming ----------------------------------------------------------------

    def arm(self, label: str, timeout: Optional[float] = None) -> None:
        with self._lock:
            if timeout is None and self._frames:
                # nested frames inherit the ENCLOSING budget by default: a
                # barrier inside a (deliberately generous) checkpoint-save
                # frame must not shrink the deadline back to step size
                timeout = self._frames[-1].timeout
            self._frames.append(_Frame(label, timeout or self.timeout))
            self._last_beat = time.monotonic()
        self._ensure_thread()

    def disarm(self) -> None:
        with self._lock:
            if self._frames:
                self._frames.pop()
            if self._frames:
                # the frame below just regained control: restart its clock
                top = self._frames[-1]
                top.deadline = time.monotonic() + top.timeout

    def tick(self, label: Optional[str] = None) -> None:
        """Fresh deadline for the top frame (call once per unit of work)."""
        with self._lock:
            if not self._frames:
                return
            top = self._frames[-1]
            if label is not None:
                top.label = label
            top.deadline = time.monotonic() + top.timeout
            self._last_beat = time.monotonic()
        self._emit_beat()

    @contextmanager
    def watch(self, label: str, timeout: Optional[float] = None):
        self.arm(label, timeout)
        try:
            yield self.tick
        finally:
            self.disarm()

    def add_on_timeout(self, hook: Callable[[str], None]) -> None:
        """Chain ``hook`` onto the timeout path (runs after any existing
        hook, before the process exit) — how the flight recorder gets its
        dump out on a hang abort. Hook failures are already contained by
        the firing path: the exit must happen regardless."""
        prev = self.on_timeout

        def chained(label: str) -> None:
            if prev is not None:
                try:
                    prev(label)
                except Exception:  # noqa: BLE001 - dying anyway; the next
                    # hook (and the exit) must still run
                    logger.exception("watchdog on_timeout hook failed")
            hook(label)

        self.on_timeout = chained

    def note_progress(self, step: int) -> None:
        with self._lock:
            self._last_step = int(step)
            self._last_beat = time.monotonic()
        self._emit_beat()

    def add_on_beat(
        self, hook: Callable[[Optional[int]], None], *,
        min_interval: float = 1.0,
    ) -> None:
        """Piggyback a liveness hook on the watchdog's OWN heartbeat: each
        ``tick``/``note_progress`` may call ``hook(last_step)``, rate-
        limited to one emit per ``min_interval`` seconds — how the elastic
        child publishes its cross-host heartbeat file at training cadence
        without a second timer thread. The hook runs OUTSIDE the lock (it
        does IO) and its failures are contained: heartbeating degrades,
        training never does."""
        self._on_beat = hook
        self._on_beat_interval = float(min_interval)

    def _emit_beat(self) -> None:
        hook = self._on_beat
        if hook is None:
            return
        now = time.monotonic()
        if (self._beat_emitted is not None
                and now - self._beat_emitted < self._on_beat_interval):
            return
        self._beat_emitted = now
        try:
            hook(self._last_step)
        except Exception:  # noqa: BLE001 - liveness IO must not hurt training
            logger.exception("watchdog on_beat hook failed")

    def heartbeat_age(self) -> Optional[float]:
        """Seconds since the last sign of life (arm/tick/note_progress) —
        the /metrics liveness gauge. None before any frame was ever armed
        (nothing is being watched, so there is no heartbeat to age)."""
        with self._lock:
            if self._last_beat is None:
                return None
            return max(0.0, time.monotonic() - self._last_beat)

    def stop(self) -> None:
        """Shut the monitor thread down (tests; production lets the daemon
        thread die with the process)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    # -- monitor ---------------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(
            target=self._run, name="step-watchdog", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval):
            with self._lock:
                if self._fired or not self._frames:
                    continue
                top = self._frames[-1]
                expired = time.monotonic() > top.deadline
                label, timeout, step = top.label, top.timeout, self._last_step
                if expired:
                    self._fired = True
            if expired:
                self._fire(label, timeout, step)
                return

    def _fire(self, label: str, timeout: float, step: Optional[int]) -> None:
        out = sys.stderr
        try:
            out.write(
                f"WATCHDOG: '{label}' exceeded {timeout:g}s "
                f"(last completed step: "
                f"{step if step is not None else 'none'}); "
                f"dumping all thread stacks and aborting.\n"
            )
            dump_all_stacks(out)
            out.flush()
        except Exception:  # noqa: BLE001 - dying anyway; the exit must happen
            pass
        if self.on_timeout is not None:
            try:
                self.on_timeout(label)
            except Exception:  # noqa: BLE001
                pass
        self._exit(self.exit_code)

    @staticmethod
    def _default_exit(code: int) -> None:
        import os

        # os._exit, not sys.exit: the main thread is stuck — possibly inside
        # a C extension — and atexit/finally would never run; the supervisor
        # needs the process GONE so it can restart it.
        os._exit(code)


# -- process-global instance (for call sites without a Trainer handle) ---------

_active: Optional[Watchdog] = None


def install(wd: Optional[Watchdog]) -> Optional[Watchdog]:
    """Install (or clear, with None) the process-global watchdog that
    barrier-level call sites pick up via :func:`current`."""
    global _active
    _active = wd
    return wd


def current() -> Optional[Watchdog]:
    return _active


@contextmanager
def watched(label: str, timeout: Optional[float] = None):
    """Arm the process-global watchdog around a block; no-op when none is
    installed (single-host debug runs stay zero-overhead)."""
    wd = current()
    if wd is None:
        yield lambda *_: None
        return
    with wd.watch(label, timeout) as tick:
        yield tick
