"""Synthetic LEARNABLE NQ-schema corpus.

The reference demonstrates fine-tuning by training on real Natural Questions
data to a quality metric with best-checkpoint selection (reference
README.md:1-51, modules/train.py:104-116, trainer/callback.py:79-108). An
egress-free environment has no NQ download, so convergence is proven on a
corpus whose answers are DERIVABLE: a model that learns beats chance by a
wide margin, a broken optimizer/loss/pipeline cannot.

Construction (one paragraph per document, 5 balanced classes):

- the QUESTION's first word encodes the class label
  (``is it yes`` -> yes, ``is it no`` -> no, ``find the needle`` -> short,
  ``describe it all`` -> long, ``nothing is here`` -> unknown);
- for ``short`` the document contains the marker word ``needle`` exactly
  once and the short answer is that word — the span heads must learn to
  point at it;
- for ``yes``/``no``/``long`` the annotated span is the whole paragraph, so
  the span heads must point at the document edges (position right after the
  first [SEP] / the final [SEP]);
- ``unknown`` lines carry no annotation (the -1,-1 spanless sentinel).

Everything else — filler words, document length, marker position — is
drawn from a seeded rng, so the mapping question->(class, span) is the ONLY
signal. Used by ``tests/test_convergence.py`` and ``bench.py --mode
converge``.
"""

from __future__ import annotations

import json
from pathlib import Path

SPECIALS = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
KEYWORDS = ["yes", "no", "find", "describe", "nothing"]
MARKER = "needle"
SUPPORT = ["is", "it", "the", "all", "here", "?", "."]
FILLERS = [
    "alpha", "bravo", "carol", "delta", "echo", "fern", "golf", "hotel",
    "india", "jade", "kilo", "lima", "mike", "norse", "oscar", "papa",
]

QUESTIONS = {
    "yes": "is it yes ?",
    "no": "is it no ?",
    "short": "find the needle ?",
    "long": "describe it all ?",
    "unknown": "nothing is here ?",
}
CLASS_CYCLE = ["yes", "no", "short", "long", "unknown"]


def write_learnable_vocab(out_dir) -> Path:
    """WordPiece vocab covering exactly the corpus' closed vocabulary (every
    word is a single whole-word piece, so word index == token index within
    the paragraph body)."""
    out_dir = Path(out_dir)
    vocab_file = out_dir / "vocab.txt"
    vocab_file.write_text(
        "\n".join(SPECIALS + KEYWORDS + [MARKER] + SUPPORT + FILLERS) + "\n"
    )
    return vocab_file


def make_learnable_line(i: int, rng) -> dict:
    """One NQ-schema json line of class ``CLASS_CYCLE[i % 5]``."""
    label = CLASS_CYCLE[i % len(CLASS_CYCLE)]

    n_body = int(rng.integers(8, 24))
    body = list(rng.choice(FILLERS, size=n_body))
    if label == "short":
        pos = int(rng.integers(0, n_body))
        body[pos] = MARKER
        # word index within document_text.split(): one leading <P> tag word
        marker_word = 1 + pos
        short_answers = [{"start_token": marker_word, "end_token": marker_word + 1}]
    else:
        short_answers = []

    words = ["<P>"] + body + ["</P>"]
    long_span = {"start_token": 0, "end_token": len(words), "candidate_index": 0}
    annotation = {
        "yes_no_answer": {"yes": "YES", "no": "NO"}.get(label, "NONE"),
        "long_answer": (
            {"start_token": -1, "end_token": -1, "candidate_index": -1}
            if label == "unknown"
            else long_span
        ),
        "short_answers": short_answers,
    }
    return {
        "example_id": str(i),
        "document_text": " ".join(words),
        "question_text": QUESTIONS[label],
        "annotations": [annotation],
        "long_answer_candidates": [
            {"start_token": 0, "end_token": len(words), "top_level": True}
        ],
    }


def write_learnable_corpus(out_path, *, n_examples: int = 200, seed: int = 0) -> Path:
    import numpy as np

    out_path = Path(out_path)
    rng = np.random.default_rng(seed)
    with open(out_path, "w") as fh:
        for i in range(n_examples):
            fh.write(json.dumps(make_learnable_line(i, rng)) + "\n")
    return out_path


class ConvergenceTP:
    """Trainer hyperparameters for the convergence harness."""

    loss = "ce"
    smooth_alpha = 0.01
    focal_alpha = 1
    focal_gamma = 2
    w_start = 1
    w_end = 1
    w_start_reg = 0.5
    w_end_reg = 0.5
    w_cls = 1
    weight_decay = 0.01
    optimizer = "adam"
    finetune = False
    best_metric = "map"
    best_order = ">"

    def __init__(self, lr: float, warmup_coef: float = 0.05):
        self.lr = lr
        self.warmup_coef = warmup_coef


def make_convergence_trainer(
    workdir,
    *,
    model_cfg,
    mesh,
    lr: float,
    n_epochs: int,
    batch: int,
    seq_len: int = 64,
    n_examples: int = 200,
    test_size: float = 0.2,
    n_jobs: int = 2,
    seed: int = 0,
    warmup_coef: float = 0.05,
):
    """Corpus -> preprocess -> datasets -> Trainer, the ONE pipeline both
    ``tests/test_convergence.py`` and ``bench.py --mode converge`` train on
    (shared so the CI proof and the on-hardware artifact cannot drift).

    ``workdir`` must exist; returns a ready Trainer whose train/test sets
    cover all five classes (stratified split).
    """
    import numpy as np

    from ..data import RawPreprocessor, SplitDataset
    from ..data.collate import make_collate_fun
    from ..losses import build_loss
    from ..models import QAModel
    from ..tokenizer import Tokenizer
    from ..train import Trainer

    workdir = Path(workdir)
    vocab = write_learnable_vocab(workdir)
    corpus = write_learnable_corpus(
        workdir / "corpus.jsonl", n_examples=n_examples, seed=seed
    )
    tokenizer = Tokenizer("bert", str(vocab), lowercase=True)
    pre = RawPreprocessor(corpus, workdir / "proc", test_size=test_size)
    _, _, (train_idx, _, test_idx, _) = pre()

    common = dict(
        tokenizer=tokenizer,
        max_seq_len=seq_len,
        max_question_len=8,
        doc_stride=max(16, seq_len - 16),
        split_by_sentence=False,
        truncate=False,
        rng=np.random.default_rng(seed),
    )
    train_ds = SplitDataset(workdir / "proc", indexes=train_idx, **common)
    test_ds = SplitDataset(workdir / "proc", indexes=test_idx, test=True, **common)

    tp = ConvergenceTP(lr, warmup_coef=warmup_coef)
    import dataclasses

    import jax

    # fit the config to the harness: the closed vocab is tiny (no point in
    # a 30k embedding) and positions must cover seq_len
    model_cfg = dataclasses.replace(
        model_cfg,
        vocab_size=max(len(tokenizer), 128),
        max_position_embeddings=max(
            model_cfg.max_position_embeddings, seq_len + 2
        ),
    )
    model = QAModel(model_cfg)
    params = model.init(
        jax.random.key(seed), np.zeros((1, 8), dtype=np.int32)
    )["params"]

    trainer = Trainer(
        model=model,
        params=params,
        loss=build_loss(tp),
        collate_fun=make_collate_fun(tokenizer, max_seq_len=seq_len),
        trainer_params=tp,
        train_dataset=train_ds,
        test_dataset=test_ds,
        mesh=mesh,
        n_epochs=n_epochs,
        train_batch_size=batch,
        test_batch_size=batch,
        batch_split=1,
        n_jobs=n_jobs,
        warmup_coef=tp.warmup_coef,
        max_grad_norm=1.0,
        seed=seed,
    )
    if len(trainer.train_dataloader) == 0:
        raise ValueError(
            f"convergence harness has zero train batches: "
            f"{len(train_idx)} train examples with drop_last at batch "
            f"{batch} — lower the batch size or raise n_examples."
        )
    return trainer
