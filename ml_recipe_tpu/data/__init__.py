from .preprocessor import LineDataExtractor, RawPreprocessor
from .datasets import DatasetItem, ChunkItem, SplitDataset, ChunkDataset, DummyDataset
from .collate import collate_fun, make_collate_fun, rebind_collate_seq
from .loader import DataLoader, ListDataloader, ShardedBatchSampler
from .bucketing import (
    BucketedBatch,
    BucketedDataLoader,
    TokenBudgetBucketer,
    auto_seq_grid,
    bucket_batch_sizes,
    parse_length_buckets,
)
from .packing import (
    PackedBatch,
    PackedDataLoader,
    SequencePacker,
    collate_packed,
    parse_sequence_packing,
)
from .device_prefetch import DevicePrefetcher

__all__ = [
    "LineDataExtractor",
    "RawPreprocessor",
    "DatasetItem",
    "ChunkItem",
    "SplitDataset",
    "ChunkDataset",
    "DummyDataset",
    "collate_fun",
    "make_collate_fun",
    "rebind_collate_seq",
    "DataLoader",
    "ListDataloader",
    "ShardedBatchSampler",
    "BucketedBatch",
    "BucketedDataLoader",
    "TokenBudgetBucketer",
    "auto_seq_grid",
    "bucket_batch_sizes",
    "parse_length_buckets",
    "PackedBatch",
    "PackedDataLoader",
    "SequencePacker",
    "collate_packed",
    "parse_sequence_packing",
    "DevicePrefetcher",
]
