from .preprocessor import LineDataExtractor, RawPreprocessor
from .datasets import DatasetItem, ChunkItem, SplitDataset, ChunkDataset, DummyDataset
from .collate import collate_fun, make_collate_fun
from .loader import DataLoader, ListDataloader, ShardedBatchSampler

__all__ = [
    "LineDataExtractor",
    "RawPreprocessor",
    "DatasetItem",
    "ChunkItem",
    "SplitDataset",
    "ChunkDataset",
    "DummyDataset",
    "collate_fun",
    "make_collate_fun",
    "DataLoader",
    "ListDataloader",
    "ShardedBatchSampler",
]
