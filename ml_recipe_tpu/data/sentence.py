"""Lightweight English sentence splitter.

Replaces the reference's nltk punkt dependency (split_dataset.py:230-241),
which requires a runtime model download — unusable in an egress-free TPU pod.
Rule-based: split after sentence-final punctuation followed by whitespace and
an upper-case/digit/quote opener, with an abbreviation guard. Boundaries only
steer chunk packing, so "reasonable" is sufficient; exact punkt parity is not
a semantic requirement.
"""

from __future__ import annotations

import re
from typing import List

_ABBREVIATIONS = {
    "mr", "mrs", "ms", "dr", "prof", "sr", "jr", "st", "no", "vs", "etc",
    "e.g", "i.e", "fig", "vol", "inc", "ltd", "co", "corp", "dept", "est",
    "approx", "jan", "feb", "mar", "apr", "jun", "jul", "aug", "sep", "sept",
    "oct", "nov", "dec", "u.s", "u.k",
}

_BOUNDARY = re.compile(r"([.!?]+)(\s+)(?=[\"'‘“(\[]?[A-Z0-9<])")


def _last_word(text: str) -> str:
    stripped = text.rstrip(".!?")
    idx = max(stripped.rfind(" "), stripped.rfind("\n"))
    return stripped[idx + 1:].lower()


def split_sentences(text: str) -> List[str]:
    """Split text into sentences; whitespace inside sentences is preserved."""
    if not text:
        return []

    sentences: List[str] = []
    last = 0
    for match in _BOUNDARY.finditer(text):
        candidate_end = match.end(1)
        prefix = text[last:candidate_end]
        word = _last_word(prefix)
        # Do not break after known abbreviations or single-letter initials.
        if word in _ABBREVIATIONS or (len(word) == 1 and word.isalpha()):
            continue
        sentences.append(text[last:match.end(2)].rstrip())
        last = match.end(2)

    tail = text[last:].strip()
    if tail:
        sentences.append(tail)

    # reachable with an empty list only for whitespace-only input (any real
    # content lands in the tail) — no sentences is the right answer there
    return sentences
