"""Dataset classes over the preprocessed corpus.

Parity targets:
- ``DatasetItem``: reference split_dataset.py:191-199.
- ``SplitDataset``: split_dataset.py:202-477 — per-example load, chunking via
  sliding-window or sentence packing, weighted random chunk sampling (answer
  chunks weighted 1 vs 1e-3 for 'unknown'), optional truncation.
- ``ChunkItem``/``ChunkDataset``: validation_dataset.py:15-319 — same chunkers
  but ALL chunks per document, with provenance for the Predictor.
- ``DummyDataset``: dummy_dataset.py:6-51 — synthetic fixed-shape QA items for
  zero-download smoke/integration runs.

TPU-first deltas:
- one shared chunking engine (``chunking.py``) instead of duplicated logic;
- an LRU token cache: the reference re-reads and re-tokenizes every document
  on every epoch (split_dataset.py:467-477, the dominant host-CPU cost); we
  cache the tokenized document keyed by example index (disabled automatically
  when BPE dropout is active, since encoding is then stochastic);
- RNG is injectable for deterministic tests / seeded runs.
"""

from __future__ import annotations

import json
import logging
from collections import OrderedDict
from dataclasses import dataclass
from pathlib import Path
from typing import List, Optional

import numpy as np

from .chunking import (
    ChunkRecord,
    assemble_input_ids,
    chunk_sampling_weights,
    encode_document,
    encode_document_by_sentences,
    pick_eval_chunk,
    sentence_chunks,
    truncate_record,
    window_chunks,
)
from .preprocessor import RawPreprocessor
from .sentence import split_sentences

logger = logging.getLogger(__name__)


@dataclass
class DatasetItem:
    example_id: str
    input_ids: List[int]
    start_id: int
    end_id: int
    label_id: int
    start_position: float
    end_position: float


@dataclass
class ChunkItem:
    """Chunk + provenance for inference (validation_dataset.py:15-39)."""

    item_id: str
    input_ids: List[int]
    start_id: int
    end_id: int
    label_id: int

    true_text: str
    true_question: str
    true_label: int
    true_start: int
    true_end: int

    question_len: int

    t2o: List[int]

    chunk_start: int
    chunk_end: int

    start_position: float
    end_position: float


class _ChunkingDatasetBase:
    """Shared document-loading + chunk-enumeration machinery."""

    def __init__(
        self,
        data_dir,
        tokenizer,
        indexes,
        *,
        max_seq_len: int = 384,
        max_question_len: int = 64,
        doc_stride: int = 128,
        test: bool = False,
        split_by_sentence: bool = False,
        truncate: bool = False,
        cache_size: int = 1024,
        rng: Optional[np.random.Generator] = None,
    ):
        self.data_dir = Path(data_dir)
        self.tokenizer = tokenizer
        self.indexes = indexes

        self.max_seq_len = max_seq_len
        self.max_question_len = max_question_len
        self.doc_stride = doc_stride

        self.labels2id = RawPreprocessor.labels2id
        self.id2labels = RawPreprocessor.id2labels

        self.test = test
        self.truncate = truncate
        self.split_by_sentence = split_by_sentence

        self.rng = rng if rng is not None else np.random.default_rng()

        bpe_dropout_active = getattr(
            getattr(tokenizer, "tokenizer", None), "dropout", None
        )
        self.cache_size = 0 if bpe_dropout_active else cache_size
        self._cache: OrderedDict = OrderedDict()

    def __len__(self) -> int:
        return len(self.indexes)

    def _load_line(self, idx: int) -> dict:
        with open(self.data_dir / f"{idx}.json", "r") as in_file:
            return json.load(in_file)

    def _encoded(self, idx: int, line: dict):
        """Tokenize document+question (cached); returns
        ``(encoded_question, per_sentence_or_flat_tokens, o2t, t2o)``."""
        if idx in self._cache:
            self._cache.move_to_end(idx)
            return self._cache[idx]

        encoded_question = self.tokenizer.encode(line["question_text"])[: self.max_question_len]

        if self.split_by_sentence:
            tokens, o2t, t2o = encode_document_by_sentences(
                self.tokenizer, line["document_text"], split_sentences
            )
        else:
            tokens, o2t, t2o = encode_document(self.tokenizer, line["document_text"])

        value = (encoded_question, tokens, o2t, t2o)
        if self.cache_size > 0:
            self._cache[idx] = value
            while len(self._cache) > self.cache_size:
                self._cache.popitem(last=False)
        return value

    def _enumerate_chunks(self, idx: int, line: dict):
        """All chunks of one document + its mapped target."""
        encoded_question, tokens, o2t, t2o = self._encoded(idx, line)

        class_label, start_position, end_position = RawPreprocessor._get_target(line)

        assert start_position <= end_position, "Before mapping."
        if start_position < 0:
            # 'unknown': there is no answer span. The reference maps -1
            # through o2t[-1] (split_dataset.py:274-275), silently training
            # the span heads toward the document's last token on whichever
            # chunk contains it; keep the spanless (-1, -1) sentinel instead
            # (the losses/metrics mask -1).
            start_position = end_position = -1
        else:
            start_position = o2t[start_position]
            end_position = o2t[end_position]
        assert start_position <= end_position, "After mapping."

        target = (class_label, start_position, end_position)

        if self.split_by_sentence:
            records = sentence_chunks(
                tokens,
                target,
                question_len=len(encoded_question),
                max_seq_len=self.max_seq_len,
            )
        else:
            records = window_chunks(
                tokens,
                target,
                question_len=len(encoded_question),
                max_seq_len=self.max_seq_len,
                doc_stride=self.doc_stride,
                first_only=self.test,
            )

        return records, encoded_question, target, t2o

    def _finalize(self, rec: ChunkRecord, encoded_question) -> List[int]:
        if self.truncate:
            rec = truncate_record(
                rec, question_len=len(encoded_question), max_seq_len=self.max_seq_len
            )

        input_ids = assemble_input_ids(
            self.tokenizer.cls_token_id, self.tokenizer.sep_token_id, encoded_question, rec
        )

        assert len(input_ids) <= self.max_seq_len or not (
            self.truncate or not self.split_by_sentence
        ), (
            f"Chunk length {len(input_ids)} exceeds limit {self.max_seq_len} "
            f"(label {rec.label}, span [{rec.start}, {rec.end}], "
            f"doc window [{rec.doc_start}, {rec.doc_end}], #sents {rec.n_sents})."
        )
        assert -1 <= rec.start <= self.max_seq_len, f"Incorrect start index: {rec.start}."
        assert -1 <= rec.end <= self.max_seq_len, f"Incorrect end index: {rec.end}."

        return input_ids, rec


class SplitDataset(_ChunkingDatasetBase):
    """Training dataset: one weighted-sampled chunk per document per epoch."""

    def __getitem__(self, idx: int) -> DatasetItem:
        idx = int(self.indexes[idx])
        line = self._load_line(idx)

        records, encoded_question, target, _ = self._enumerate_chunks(idx, line)
        class_label = target[0]

        if self.test:
            pick = pick_eval_chunk(records, class_label)
        else:
            weights = chunk_sampling_weights(records)
            pick = int(self.rng.choice(np.arange(len(records)), p=weights))

        input_ids, rec = self._finalize(records[pick], encoded_question)

        return DatasetItem(
            example_id=line["example_id"],
            input_ids=input_ids,
            start_id=rec.start,
            end_id=rec.end,
            label_id=self.labels2id[rec.label],
            start_position=rec.start / self.max_seq_len,
            end_position=rec.end / self.max_seq_len,
        )


class ChunkDataset(_ChunkingDatasetBase):
    """Validation dataset: ALL chunks per document, with provenance."""

    def __getitem__(self, idx: int) -> List[ChunkItem]:
        idx = int(self.indexes[idx])
        line = self._load_line(idx)

        records, encoded_question, target, t2o = self._enumerate_chunks(idx, line)
        class_label, start_position, end_position = target

        chunks: List[ChunkItem] = []
        for rec in records:
            input_ids, rec = self._finalize(rec, encoded_question)
            chunks.append(
                ChunkItem(
                    item_id=line["example_id"],
                    input_ids=input_ids,
                    start_id=rec.start,
                    end_id=rec.end,
                    label_id=self.labels2id[rec.label],
                    true_text=line["document_text"],
                    true_question=line["question_text"],
                    question_len=len(encoded_question),
                    t2o=t2o,
                    chunk_start=rec.doc_start,
                    chunk_end=rec.doc_end,
                    true_label=self.labels2id[class_label],
                    true_start=start_position,
                    true_end=end_position,
                    start_position=rec.start / self.max_seq_len,
                    end_position=rec.end / self.max_seq_len,
                )
            )

        return chunks


class DummyDataset:
    """Synthetic random-token QA items at fixed shape (dummy_dataset.py:6-51)."""

    def __init__(
        self,
        data_dir=None,
        tokenizer=None,
        indexes=None,
        *,
        max_seq_len: int = 384,
        max_question_len: int = 64,
        dataset_len: int = 10000,
        rng: Optional[np.random.Generator] = None,
        **kwargs,
    ):
        self.tokenizer = tokenizer
        self.dataset_len = dataset_len

        self.max_seq_len = max_seq_len
        self.max_question_len = max_question_len

        # Items are derived from (base_seed, index) so content is a pure
        # function of the index — the reference drew from a shared generator
        # per access (dummy_dataset.py:20-24), which under a threaded loader
        # makes item content depend on scheduling (np.random.Generator is
        # also not thread-safe).
        seed_rng = rng if rng is not None else np.random.default_rng()
        self.base_seed = int(seed_rng.integers(2 ** 31))

        self.w_ids = (
            [
                self.tokenizer.pad_token_id,
                self.tokenizer.sep_token_id,
                self.tokenizer.cls_token_id,
            ]
            if tokenizer is not None
            else None
        )

    def __len__(self) -> int:
        return self.dataset_len

    def _delete_special(self, ids: np.ndarray) -> np.ndarray:
        assert self.w_ids is not None, (
            f"Dataset {type(self).__name__} was initialized with None tokenizer."
        )
        for w_id in self.w_ids:
            ids[ids == w_id] = self.tokenizer.unk_token_id
        return ids

    def __getitem__(self, index: int = 0) -> DatasetItem:
        document_len = self.max_seq_len - self.max_question_len - 3

        rng = np.random.default_rng(
            np.random.SeedSequence([self.base_seed, int(index)])
        )
        question_ids = self._delete_special(
            rng.integers(1, len(self.tokenizer), self.max_question_len)
        ).tolist()
        document_ids = self._delete_special(
            rng.integers(1, len(self.tokenizer), document_len)
        ).tolist()

        input_ids = (
            [self.tokenizer.cls_token_id]
            + question_ids
            + [self.tokenizer.sep_token_id]
            + document_ids
            + [self.tokenizer.sep_token_id]
        )

        return DatasetItem(
            example_id="None",
            input_ids=input_ids,
            start_id=0,
            end_id=self.max_seq_len - 1,
            label_id=0,
            start_position=0.0,
            end_position=1.0,
        )
