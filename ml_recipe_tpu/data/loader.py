"""Host-side input pipeline.

Replaces three reference mechanisms with one SPMD-aware design:

- ``torch.utils.data.DataLoader`` + worker processes (trainer.py:168-181):
  here a thread-pool prefetch pipeline producing fixed-shape numpy batches.
- ``DistributedSampler`` / ``RandomSampler`` / ``WeightedRandomSampler``
  (trainer.py:150-166): here :class:`ShardedBatchSampler` — every host draws
  the SAME deterministic global index sequence (seeded per epoch) and takes
  its own contiguous slice of each global batch, so the union over hosts is
  exactly one global batch per step with no coordination traffic.
- ``ListDataloader`` (utils/list_dataloader.py): mp.Pool streaming of
  variable chunks-per-doc for inference; here a thread/process pool feeding a
  bounded queue, re-batched to a fixed batch size across document boundaries.
"""

from __future__ import annotations

import logging
import queue
import threading
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterator, Optional, Sequence

import numpy as np

from ..resilience.faults import fire as _fault
from ..resilience.faults import retry_transient

logger = logging.getLogger(__name__)


class DataLoaderWorkerError(RuntimeError):
    """An async loader worker died. Carries the worker's formatted traceback
    in the message (and chains the original via ``__cause__``): the
    consumer raises on ITS thread, and without this the only record of
    where the worker actually failed would be lost across the queue."""


def _read_with_retry(dataset, index: int, *, retries: int):
    """One dataset item read, with bounded retry + backoff on transient
    ``OSError`` (flaky network FS, evicted page cache, injected drills).
    Non-OSError failures (bugs) propagate immediately — retrying those only
    delays the real traceback."""

    def read():
        _fault("loader.read")
        return dataset[index]

    return retry_transient(
        read, retries=retries, exceptions=(OSError,),
        what=f"dataset read [{index}]",
    )


class ShardedBatchSampler:
    """Deterministic per-host batch index sampler.

    Each epoch: build one global ordering (shuffled, or weighted-with-
    replacement when ``weights`` is given — WeightedRandomSampler parity,
    trainer.py:159-160), chop into global batches of ``global_batch_size``,
    and yield this host's ``[process_index]``-th slice of each. ``drop_last``
    mirrors the reference's train dataloader (trainer.py:105).
    """

    def __init__(
        self,
        dataset_len: int,
        global_batch_size: int,
        *,
        process_index: int = 0,
        process_count: int = 1,
        shuffle: bool = True,
        weights: Optional[Sequence[float]] = None,
        drop_last: bool = True,
        pad_last: bool = False,
        seed: int = 0,
    ):
        assert global_batch_size % process_count == 0, (
            f"global batch {global_batch_size} must divide over {process_count} hosts"
        )
        self.dataset_len = dataset_len
        self.global_batch_size = global_batch_size
        self.local_batch_size = global_batch_size // process_count
        self.process_index = process_index
        self.process_count = process_count
        self.shuffle = shuffle
        self.weights = None if weights is None else np.asarray(weights, dtype=np.float64)
        self.drop_last = drop_last
        # pad_last keeps the final partial batch at the full static shape by
        # repeating the last index (fixed-shape discipline: one compiled
        # program serves eval too). Consumers trim with `valid_count(b)`.
        self.pad_last = pad_last and not drop_last
        self.seed = seed

    def __len__(self) -> int:
        if self.drop_last:
            return self.dataset_len // self.global_batch_size
        return (self.dataset_len + self.global_batch_size - 1) // self.global_batch_size

    def epoch_indices(self, epoch: int) -> np.ndarray:
        rng = np.random.default_rng(np.random.SeedSequence([self.seed, epoch]))
        if self.weights is not None:
            p = self.weights / self.weights.sum()
            return rng.choice(self.dataset_len, size=self.dataset_len, replace=True, p=p)
        if self.shuffle:
            return rng.permutation(self.dataset_len)
        return np.arange(self.dataset_len)

    def valid_count(self, batch_index: int) -> int:
        """Number of real (non-padding) rows in the given *global* batch."""
        remaining = self.dataset_len - batch_index * self.global_batch_size
        return int(min(self.global_batch_size, max(remaining, 0)))

    def __call__(self, epoch: int) -> Iterator[np.ndarray]:
        indices = self.epoch_indices(epoch)
        n_batches = len(self)
        for b in range(n_batches):
            global_batch = indices[b * self.global_batch_size : (b + 1) * self.global_batch_size]
            if len(global_batch) < self.global_batch_size:
                if self.drop_last:
                    return
                if self.pad_last:
                    pad = np.full(
                        self.global_batch_size - len(global_batch),
                        global_batch[-1] if len(global_batch) else 0,
                        dtype=indices.dtype,
                    )
                    global_batch = np.concatenate([global_batch, pad])
            lo = self.process_index * self.local_batch_size
            hi = lo + self.local_batch_size
            yield global_batch[lo:hi]


class DataLoader:
    """Prefetching map-style loader producing collated fixed-shape batches."""

    def __init__(
        self,
        dataset,
        sampler: ShardedBatchSampler,
        collate_fun: Callable,
        *,
        n_jobs: int = 4,
        prefetch: int = 4,
        read_retries: int = 3,
    ):
        self.dataset = dataset
        self.sampler = sampler
        self.collate_fun = collate_fun
        self.n_jobs = max(1, n_jobs)
        self.prefetch = max(1, prefetch)
        self.read_retries = max(0, read_retries)
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __len__(self) -> int:
        return len(self.sampler)

    def real_rows(self, batch_index: int) -> int:
        """Number of real (non-padding) rows in the given batch — with
        ``pad_last`` the final partial batch repeats its last index up to
        the static shape, and consumers must exclude those rows from
        loss/metric averaging (the bucketed loader reports the same count
        per batch via ``BucketedBatch.real_rows``)."""
        return self.sampler.valid_count(batch_index)

    def _load_batch(self, batch_indices: np.ndarray):
        items = [
            _read_with_retry(self.dataset, int(i), retries=self.read_retries)
            for i in batch_indices
        ]
        return self.collate_fun(items)

    def __iter__(self):
        batches = list(self.sampler(self._epoch))
        if not batches:
            return
        with ThreadPoolExecutor(max_workers=self.n_jobs) as pool:
            futures: list = []
            it = iter(batches)
            for _ in range(min(self.prefetch, len(batches))):
                futures.append(pool.submit(self._load_batch, next(it)))
            pending = len(batches) - len(futures)
            i = 0
            while futures:
                fut = futures.pop(0)
                if pending > 0:
                    futures.append(pool.submit(self._load_batch, next(it)))
                    pending -= 1
                yield fut.result()
                i += 1


class ListDataloader:
    """Async loader for datasets whose ``__getitem__`` returns a LIST of chunks.

    Parity target: utils/list_dataloader.py:9-97 — a worker pool expands one
    document into its chunk list and streams chunks into a bounded queue; the
    consumer re-batches to a fixed ``batch_size`` across document boundaries.
    Exists because variable chunks-per-doc breaks the 1-item→1-row assumption
    of the map-style loader (reference validate.py:37 todo).
    """

    _SENTINEL = object()

    def __init__(
        self,
        dataset,
        batch_size: int,
        *,
        n_jobs: int = 4,
        collate_fun: Optional[Callable] = None,
        buffer_size: int = 1024,
        shuffle: bool = False,
        seed: int = 0,
        read_retries: int = 3,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fun = collate_fun
        self.n_jobs = max(1, n_jobs)
        self.buffer_size = buffer_size
        self.shuffle = shuffle
        self.seed = seed
        self.read_retries = max(0, read_retries)

    def process_batch(self, batch):
        return self.collate_fun(batch) if self.collate_fun is not None else batch

    def __iter__(self):
        idxs = np.arange(len(self.dataset))
        if self.shuffle:
            np.random.default_rng(self.seed).shuffle(idxs)

        q: queue.Queue = queue.Queue(maxsize=self.buffer_size)
        errors: list = []
        done = threading.Event()

        def read(i: int):
            return _read_with_retry(self.dataset, i, retries=self.read_retries)

        def producer():
            try:
                with ThreadPoolExecutor(max_workers=self.n_jobs) as pool:
                    for chunks in pool.map(read, [int(i) for i in idxs]):
                        for chunk in chunks:
                            q.put(chunk)
            except Exception as e:  # surface worker errors to the consumer
                # capture the traceback HERE: the exception is re-raised on
                # the consumer thread, where this stack no longer exists
                tb = traceback.format_exc()
                logger.error(f"ListDataloader worker failed:\n{tb}")
                errors.append((e, tb))
            finally:
                done.set()
                q.put(self._SENTINEL)

        thread = threading.Thread(target=producer, daemon=True)
        thread.start()

        batch = []
        while True:
            chunk = q.get()
            if chunk is self._SENTINEL:
                break
            batch.append(chunk)
            if len(batch) == self.batch_size:
                yield self.process_batch(batch)
                batch = []

        if errors:
            e, tb = errors[0]
            raise DataLoaderWorkerError(
                f"async loader worker failed: {e!r}\n"
                f"--- worker traceback ---\n{tb}"
            ) from e

        if batch:
            yield self.process_batch(batch)

        thread.join()
