"""Length-bucketed token-budget batching for the training/eval input path.

The collate path pads every batch to the static global ``max_seq_len``
(collate.py) so one compiled program serves the whole run — but NQ
sliding-window chunks are mostly shorter than the cap, and every pad token
burns real attention+FFN FLOPs on the device. The serving subsystem already
solved this with a SMALL FIXED GRID of pre-compiled shapes
(serve/bucketing.py); this module brings the same discipline to training and
offline eval:

- items are routed to the smallest bucket seq that fits them and padded only
  to the BUCKET, not the global max;
- the per-bucket batch size scales inversely with the bucket seq so every
  step carries (approximately) the same number of tokens — the TOKEN BUDGET
  — keeping step time and HBM footprint roughly constant across buckets;
- the whole epoch is served by ``len(grid)`` compiled programs (jit caches
  one executable per input shape; the PR-2 autotune cache makes each bucket
  compile zero-probe on a warm restart).

Sampling-order preservation: the bucketed loader walks the SAME deterministic
epoch ordering the ``ShardedBatchSampler`` draws (shuffled or
weighted-with-replacement), assigning items to buckets in that order — so
answer upsampling and epoch determinism survive; only batch *composition*
changes (each batch is drawn from one bucket's arrival queue).

Multi-host note: bucket composition depends on item CONTENT (lengths), which
every host must agree on for step shapes to stay in lockstep. Multi-host
loaders derive the identical per-epoch bucket plan from the SHARED LENGTH
ORACLE (``data/packing.oracle_read`` — item reads become a pure function of
``(epoch, index)``), then each host collates only its contiguous row slice
of every planned global batch; see :meth:`BucketedDataLoader._iter_oracle`.
"""

from __future__ import annotations

import logging
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from .collate import rebind_collate_seq
from .loader import _read_with_retry

logger = logging.getLogger(__name__)

DEFAULT_NUM_BUCKETS = 4


def auto_seq_grid(max_seq_len: int, n_buckets: int = DEFAULT_NUM_BUCKETS) -> List[int]:
    """Evenly spaced seq grid ending exactly at ``max_seq_len``, each edge
    rounded UP to a multiple of 8 (lane-friendly shapes; rounding down could
    strand items between buckets). max 512 -> [128, 256, 384, 512]."""
    if max_seq_len < 8:
        return [int(max_seq_len)]
    grid = set()
    for k in range(1, max(1, n_buckets) + 1):
        edge = int(-(-(max_seq_len * k) // (n_buckets * 8)) * 8)  # ceil to 8
        grid.add(min(edge, int(max_seq_len)))
    grid.add(int(max_seq_len))
    return sorted(grid)


def parse_length_buckets(spec, max_seq_len: Optional[int] = None) -> Optional[List[int]]:
    """Flag domain of ``--length_buckets``: ``off``/``none``/``0`` (or None)
    -> None (pad-to-max batching, exactly today's behavior); ``auto`` ->
    :func:`auto_seq_grid`; ``"128,256,384"`` -> explicit edges. A list/tuple
    passes through. When ``max_seq_len`` is known the grid is extended to
    cover it — an item longer than every bucket would have nowhere to go."""
    if spec is None:
        return None
    if isinstance(spec, (list, tuple)):
        grid = [int(s) for s in spec]
    else:
        s = str(spec).strip().lower()
        if s in ("off", "none", "0", "false", ""):
            return None
        if s == "auto":
            if max_seq_len is None:
                raise ValueError("length_buckets=auto requires max_seq_len")
            grid = auto_seq_grid(int(max_seq_len))
        else:
            try:
                grid = [int(p) for p in s.split(",") if p.strip()]
            except ValueError:
                raise ValueError(
                    f"bad length_buckets spec {spec!r} (want 'off', 'auto', "
                    f"or comma-separated seq edges like '128,256,384,512')"
                ) from None
    if not grid:
        return None
    if any(g < 8 for g in grid):
        raise ValueError(f"length_buckets edges must be >= 8, got {sorted(grid)}")
    grid = sorted(set(grid))
    if max_seq_len is not None:
        if grid[-1] > int(max_seq_len):
            # a bucket past the static cap would pad batches beyond the
            # model's position table — hard error, never a silent clamp
            # (the repo-wide position-table convention)
            raise ValueError(
                f"length_buckets edge {grid[-1]} exceeds max_seq_len "
                f"{int(max_seq_len)} (batches would outgrow the model's "
                f"position table)"
            )
        if grid[-1] < int(max_seq_len):
            grid.append(int(max_seq_len))
    return grid


def bucket_batch_sizes(
    seq_grid: Sequence[int], token_budget: int, *, multiple: int = 1
) -> Dict[int, int]:
    """Per-bucket batch sizes holding ``batch * seq`` at (or just under) the
    token budget, rounded DOWN to ``multiple`` (the product of ``batch_split``
    and the mesh data-axis size — every bucket batch must micro-split and
    shard exactly like the pad-to-max batch does). Never below ``multiple``:
    a bucket must stay runnable even when the budget is too small for it."""
    multiple = max(1, int(multiple))
    sizes = {}
    for seq in seq_grid:
        b = (int(token_budget) // int(seq)) // multiple * multiple
        sizes[int(seq)] = max(b, multiple)
    return sizes


class BucketedBatch(NamedTuple):
    """One collated batch padded to its bucket: ``rows`` total rows of
    ``seq`` tokens, of which the first ``real_rows`` are real examples (the
    rest repeat the last real row — eval tail padding; train batches are
    always full)."""

    inputs: dict
    labels: dict
    seq: int
    real_rows: int
    rows: int


class TokenBudgetBucketer:
    """Streaming item -> bucket accumulator (shared by the bucketed train
    loader and the predictor's chunk batching). ``add`` returns a full
    ``(seq, items)`` group when the item completes its bucket's batch,
    ``flush`` drains the partial tails in grid order."""

    def __init__(self, seq_grid: Sequence[int], batch_sizes: Dict[int, int]):
        self.seq_grid = sorted(int(s) for s in seq_grid)
        self.batch_sizes = {int(k): int(v) for k, v in batch_sizes.items()}
        self._pending: Dict[int, list] = {s: [] for s in self.seq_grid}

    def bucket_for(self, length: int) -> int:
        """Smallest bucket seq >= ``length``; the TOP bucket for anything
        longer (collate then enforces the hard cap, exactly as it does on
        the unbucketed path)."""
        for seq in self.seq_grid:
            if length <= seq:
                return seq
        return self.seq_grid[-1]

    def add(self, length: int, item):
        seq = self.bucket_for(length)
        pending = self._pending[seq]
        pending.append(item)
        if len(pending) >= self.batch_sizes[seq]:
            self._pending[seq] = []
            return seq, pending
        return None

    def flush(self):
        for seq in self.seq_grid:
            pending = self._pending[seq]
            if pending:
                self._pending[seq] = []
                yield seq, pending


class BucketedDataLoader:
    """Prefetching loader producing bucket-homogeneous collated batches.

    Walks ``sampler.epoch_indices(epoch)`` (the exact ordering the plain
    :class:`~ml_recipe_tpu.data.loader.DataLoader` batches — weighted
    sampling preserved), reads items through the same retrying thread pool,
    and groups them by length bucket under the token budget. Train mode
    (``pad_last=False``) drops the partial bucket tails at epoch end
    (drop_last parity: no padding rows ever reach the loss); eval mode
    (``pad_last=True``) pads tails by repeating the last real item and
    reports ``real_rows`` so consumers trim before metric averaging.

    Multi-host (``sampler.process_count > 1``): every host derives the SAME
    epoch bucket plan from the shared length oracle
    (data/packing.oracle_read — item lengths become a pure function of the
    index) and collates only its contiguous row slice of each planned
    global bucket batch, so step shapes stay in lockstep across hosts with
    zero coordination traffic. ``rows``/``real_rows`` on the emitted
    batches stay GLOBAL counts.
    """

    def __init__(
        self,
        dataset,
        sampler,
        collate_fun,
        *,
        seq_grid: Sequence[int],
        token_budget: Optional[int] = None,
        batch_multiple: int = 1,
        n_jobs: int = 4,
        read_window: Optional[int] = None,
        read_retries: int = 3,
        pad_last: bool = False,
    ):
        self.process_index = int(getattr(sampler, "process_index", 0))
        self.process_count = int(getattr(sampler, "process_count", 1))
        self.dataset = dataset
        self.sampler = sampler
        self.collate_fun = collate_fun
        self.seq_grid = sorted(int(s) for s in seq_grid)
        self.token_budget = int(
            token_budget
            if token_budget is not None
            else sampler.global_batch_size * self.seq_grid[-1]
        )
        self.n_jobs = max(1, n_jobs)
        # items kept in flight with the reader pool (covers several batches
        # of the LARGEST-batch bucket so short-item bursts don't starve it)
        self.read_window = (
            int(read_window) if read_window is not None else self.n_jobs * 8
        )
        self.read_retries = max(0, read_retries)
        self.pad_last = pad_last
        self._epoch = 0
        self._collates: Dict[int, object] = {}
        self._last_stats: Optional[dict] = None
        # planning-meta cache shared with data/packing's planners:
        # (length, start_id, end_id) tuples (the bucketer reads only
        # the length column), keyed by index or (epoch_key, index)
        self._len_cache: Dict[Any, tuple] = {}
        self.rescale(batch_multiple)

    def rescale(self, batch_multiple: int) -> Dict[int, int]:
        """(Re)derive the per-bucket batch sizes for a new divisibility
        multiple — the HBM pre-flight calls this after raising
        ``batch_split`` (must happen before iteration starts)."""
        self.batch_multiple = max(1, int(batch_multiple))
        if self.process_count > 1 and self.batch_multiple % self.process_count:
            raise ValueError(
                f"batch_multiple {self.batch_multiple} must divide over "
                f"{self.process_count} hosts (each host collates its "
                f"contiguous row slice of every planned global bucket batch)"
            )
        self.batch_sizes = bucket_batch_sizes(
            self.seq_grid, self.token_budget, multiple=self.batch_multiple
        )
        return self.batch_sizes

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __len__(self) -> int:
        """UPPER-BOUND step estimate: every bucket batch carries at least
        ``sampler.global_batch_size`` rows (batch scales inversely with
        seq), so an epoch takes at most as many steps as the pad-to-max
        path — which is what the LR schedule and progress displays use."""
        return len(self.sampler)

    def planned_epoch_steps(self, epoch: int) -> int:
        """Planned batch count of one epoch: simulate the bucketer over the
        epoch's item lengths (the shared ``plan_scaled_count`` skeleton —
        each unique index read once, cached; the dataset's chunk-sampling
        RNG is shielded during the reads; corpora past
        ``PLAN_SAMPLE_ITEMS`` simulate on the epoch ordering's prefix and
        scale). This is what the LR schedule should size against —
        ``len(self)`` is the pad-to-max UPPER BOUND and overshoots by ~the
        per-bucket batch scaling (the end-of-epoch-1 trainer warning used
        to fire on exactly that gap)."""
        from .packing import plan_scaled_count

        tail = [0]

        def simulate(lengths):
            bucketer = TokenBudgetBucketer(self.seq_grid, self.batch_sizes)
            batches = 0
            for length in lengths:
                if bucketer.add(length, None) is not None:
                    batches += 1
            if self.pad_last:
                tail[0] = sum(1 for _ in bucketer.flush())
            return batches

        return plan_scaled_count(
            self.dataset, self.sampler, epoch, cache=self._len_cache,
            n_jobs=self.n_jobs, read_retries=self.read_retries,
            simulate=simulate, oracle=self.process_count > 1,
        ) + tail[0]

    def _collate_for(self, seq: int):
        collate = self._collates.get(seq)
        if collate is None:
            collate = rebind_collate_seq(self.collate_fun, seq)
            self._collates[seq] = collate
        return collate

    def _emit(self, seq: int, items: list, stats: dict, *, real_rows=None):
        real = len(items) if real_rows is None else int(real_rows)
        out = self._collate_for(seq)(items)
        inputs, labels = out[0], out[1]
        rows = len(items)
        stats["real_tokens"] += sum(len(it.input_ids) for it in items[:real])
        stats["bucket_tokens"] += rows * seq
        stats["padmax_tokens"] += real * self.seq_grid[-1]
        stats["batches"] += 1
        stats["items"] += real
        return BucketedBatch(
            inputs=inputs, labels=labels, seq=seq, real_rows=real, rows=rows
        )

    def _iter_oracle(self):
        """Multi-host epoch: plan globally from oracle lengths, collate the
        local row slice (the bucketed twin of
        ``PackedDataLoader._iter_oracle``). The plan — which items form
        which (seq, rows) batch, in which order — is a pure function of the
        deterministic epoch ordering and the oracle lengths, so every host
        computes it identically and per-step shapes stay in lockstep."""
        from .packing import (
            _oracle_epoch_key,
            oracle_epoch_lengths,
            oracle_read,
        )

        indices = [int(i) for i in self.sampler.epoch_indices(self._epoch)]
        self._last_stats = stats = {
            "real_tokens": 0,
            "bucket_tokens": 0,
            "padmax_tokens": 0,
            "batches": 0,
            "items": 0,
            "dropped_items": 0,
        }
        lengths = oracle_epoch_lengths(
            self.dataset, indices, cache=self._len_cache,
            n_jobs=self.n_jobs, read_retries=self.read_retries,
            epoch=self._epoch,
        )
        ek = _oracle_epoch_key(self.dataset, self._epoch)
        bucketer = TokenBudgetBucketer(self.seq_grid, self.batch_sizes)
        plan = []  # (seq, [(index, length)], real)
        for idx, length in zip(indices, lengths):
            emitted = bucketer.add(length, (idx, length))
            if emitted is not None:
                plan.append((emitted[0], emitted[1], len(emitted[1])))
        for seq, tail_items in bucketer.flush():
            if self.pad_last:
                real = len(tail_items)
                pad = self.batch_sizes[seq] - real
                plan.append((seq, tail_items + [tail_items[-1]] * pad, real))
            else:
                stats["dropped_items"] += len(tail_items)

        def submit(pool, entries):
            rows = len(entries)
            local_rows = rows // self.process_count
            lo = self.process_index * local_rows
            return [
                pool.submit(
                    oracle_read, self.dataset, idx,
                    retries=self.read_retries, epoch=ek,
                )
                for idx, _ in entries[lo:lo + local_rows]
            ]

        # ONE pool for the epoch, reads submitted a batch ahead (mirrors
        # the single-process path's sliding read window): the next batch's
        # reads overlap this batch's collate and the device step
        with ThreadPoolExecutor(max_workers=self.n_jobs) as pool:
            pending: deque = deque()
            for i in range(min(2, len(plan))):
                pending.append(submit(pool, plan[i][1]))
            for i, (seq, entries, real) in enumerate(plan):
                futures = pending.popleft()
                if i + 2 < len(plan):
                    pending.append(submit(pool, plan[i + 2][1]))
                items = [f.result() for f in futures]
                out = self._collate_for(seq)(items)
                rows = len(entries)
                stats["real_tokens"] += sum(
                    length for _, length in entries[:real]
                )
                stats["bucket_tokens"] += rows * seq
                stats["padmax_tokens"] += real * self.seq_grid[-1]
                stats["batches"] += 1
                stats["items"] += real
                yield BucketedBatch(
                    inputs=out[0], labels=out[1], seq=seq, real_rows=real,
                    rows=rows,
                )
        if stats["dropped_items"]:
            logger.info(
                "Bucketed epoch dropped %d partial-bucket tail items "
                "(drop_last parity; they re-enter next epoch's shuffle).",
                stats["dropped_items"],
            )

    def __iter__(self):
        if self.process_count > 1:
            yield from self._iter_oracle()
            return
        indices = [int(i) for i in self.sampler.epoch_indices(self._epoch)]
        self._last_stats = stats = {
            "real_tokens": 0,
            "bucket_tokens": 0,
            "padmax_tokens": 0,
            "batches": 0,
            "items": 0,
            "dropped_items": 0,
        }
        bucketer = TokenBudgetBucketer(self.seq_grid, self.batch_sizes)
        if indices:
            with ThreadPoolExecutor(max_workers=self.n_jobs) as pool:

                def read(i):
                    return _read_with_retry(
                        self.dataset, i, retries=self.read_retries
                    )

                futures: deque = deque()
                it = iter(indices)
                for idx in indices[: self.read_window]:
                    futures.append(pool.submit(read, idx))
                    next(it)
                while futures:
                    # results are consumed in SUBMISSION order — the epoch
                    # ordering is what bucket assignment must follow
                    item = futures.popleft().result()
                    nxt = next(it, None)
                    if nxt is not None:
                        futures.append(pool.submit(read, nxt))
                    emitted = bucketer.add(len(item.input_ids), item)
                    if emitted is not None:
                        yield self._emit(emitted[0], emitted[1], stats)
        for seq, items in bucketer.flush():
            if self.pad_last:
                real = len(items)
                pad = self.batch_sizes[seq] - real
                yield self._emit(
                    seq, items + [items[-1]] * pad, stats, real_rows=real
                )
            else:
                stats["dropped_items"] += len(items)
        if stats["dropped_items"]:
            logger.info(
                "Bucketed epoch dropped %d partial-bucket tail items "
                "(drop_last parity; they re-enter next epoch's shuffle).",
                stats["dropped_items"],
            )

    @property
    def epoch_stats(self) -> Optional[dict]:
        """Token accounting of the last (or in-progress) epoch:
        ``padding_waste_pct`` is the pad-token share of what the device
        actually ran; ``padmax_waste_pct`` is what the pad-to-max path
        would have wasted on the same items."""
        s = self._last_stats
        if not s:
            return None
        out = dict(s)
        if s["bucket_tokens"]:
            out["padding_waste_pct"] = round(
                100.0 * (1.0 - s["real_tokens"] / s["bucket_tokens"]), 2
            )
        if s["padmax_tokens"]:
            out["padmax_waste_pct"] = round(
                100.0 * (1.0 - s["real_tokens"] / s["padmax_tokens"]), 2
            )
        return out


def synthetic_qa_batch(batch: int, seq: int):
    """Shape-only host ``(inputs, labels)`` in the QA collate schema
    (collate.py's fixed key set) — the per-bucket HBM pre-flight lowers and
    compiles each bucket's train step from these before the first real batch
    exists; jit caches by shape/dtype, so these compiles ARE the training
    compiles."""
    inputs = {
        "input_ids": np.ones((batch, seq), dtype=np.int32),
        "attention_mask": np.ones((batch, seq), dtype=np.int32),
        "token_type_ids": np.zeros((batch, seq), dtype=np.int32),
    }
    labels = {
        "start_class": np.zeros((batch,), dtype=np.int32),
        "end_class": np.zeros((batch,), dtype=np.int32),
        "start_reg": np.zeros((batch,), dtype=np.float32),
        "end_reg": np.zeros((batch,), dtype=np.float32),
        "cls": np.zeros((batch,), dtype=np.int32),
    }
    return inputs, labels
