"""One-time corpus preparation for the TF2.0-QA (Natural Questions) JSONL.

Parity target: reference ``modules/model/dataset/split_dataset.py:22-188``:
- ``LineDataExtractor``: random-access JSONL reader (split_dataset.py:22-47).
- ``RawPreprocessor``: per-line target extraction into the 5-class label space
  {yes,no,short,long,unknown} + answer span (split_dataset.py:74-122), one
  ``{i}.json`` record per example + pickled ``label.info``
  (split_dataset.py:124-154), and a stratified-per-class 95/5 train/test split
  pickled to ``split.info`` (split_dataset.py:156-188).

Deltas from the reference:
- line offsets are indexed once instead of ``linecache`` + ``wc -l`` shell-out;
- the stratified split is first-party numpy (no sklearn), deterministic via a
  fixed-seed Generator (reference used ``train_test_split(random_state=0)``).
"""

from __future__ import annotations

import json
import logging
import os
import pickle
from collections import defaultdict
from pathlib import Path
from typing import Tuple

import numpy as np

logger = logging.getLogger(__name__)


class LineDataExtractor:
    """Random access to a JSONL corpus by line number."""

    def __init__(self, data_path):
        self.data_path = str(data_path)

        logger.info(f"Indexing lines of file {self.data_path}...")
        self._offsets = [0]
        with open(self.data_path, "rb") as fh:
            for line in fh:
                self._offsets.append(self._offsets[-1] + len(line))
        self._offsets.pop()
        logger.info(f"Line number is {len(self._offsets)}.")

    def __len__(self) -> int:
        return len(self._offsets)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __getitem__(self, idx: int) -> dict:
        with open(self.data_path, "rb") as fh:
            fh.seek(self._offsets[idx])
            return json.loads(fh.readline())


class RawPreprocessor:
    labels2id = {k: i for i, k in enumerate(["yes", "no", "short", "long", "unknown"])}
    id2labels = {i: k for k, i in labels2id.items()}

    def __init__(self, raw_json, out_dir, *, clear: bool = False, test_size: float = 0.05):
        self.raw_json = raw_json
        self.out_dir = Path(out_dir)
        self.test_size = test_size

        os.makedirs(self.out_dir, exist_ok=True)

        self.label_info_path = self.out_dir / "label.info"
        self.split_info_path = self.out_dir / "split.info"

        if clear:
            for rm_file in self.out_dir.glob("*"):
                os.remove(rm_file)

        self._extractor = None

    @property
    def data_extractor(self) -> LineDataExtractor:
        if self._extractor is None:
            self._extractor = LineDataExtractor(self.raw_json)
        return self._extractor

    # -- record extraction ----------------------------------------------------

    @staticmethod
    def _process_line(raw_line: dict) -> dict:
        """Flatten one NQ example (split_dataset.py:74-99 field contract)."""
        line = {}

        document_text = raw_line["document_text"].split()

        line["document_text"] = raw_line["document_text"]
        line["question_text"] = raw_line["question_text"]
        line["example_id"] = raw_line["example_id"]

        annotations = raw_line["annotations"][0]

        line["yes_no_answer"] = annotations["yes_no_answer"]

        start = annotations["long_answer"]["start_token"]
        end = annotations["long_answer"]["end_token"]
        line["long_answer"] = "NONE" if start == end else document_text[start:end]
        line["long_answer_start"] = start
        line["long_answer_end"] = end
        line["long_answer_index"] = annotations["long_answer"]["candidate_index"]

        line["short_answers"] = annotations["short_answers"]

        line["long_answer_candidates"] = raw_line["long_answer_candidates"]

        return line

    @staticmethod
    def _get_target(line: dict) -> Tuple[str, int, int]:
        """5-class label + span (split_dataset.py:101-122 priority order)."""
        if line["yes_no_answer"] in ["YES", "NO"]:
            class_label = line["yes_no_answer"].lower()
            start_position = line["long_answer_start"]
            end_position = line["long_answer_end"]
        elif line["short_answers"]:
            class_label = "short"
            short_answers = line["short_answers"]
            start_position = short_answers[0]["start_token"]
            end_position = short_answers[0]["end_token"]
        elif line["long_answer_index"] != -1:
            class_label = "long"
            start_position = line["long_answer_start"]
            end_position = line["long_answer_end"]
        else:
            class_label = "unknown"
            start_position = -1
            end_position = -1

        return class_label, start_position, end_position

    # -- main entry -----------------------------------------------------------

    def __call__(self):
        if self.label_info_path.exists():
            with open(self.label_info_path, "rb") as in_file:
                labels_counter, labels = pickle.load(in_file)
            logger.info(f"Labels info was loaded from {self.label_info_path}.")
        else:
            labels_counter: dict = defaultdict(int)
            labels = np.zeros((len(self.data_extractor),))

            for line_i, raw in enumerate(self.data_extractor):
                line = RawPreprocessor._process_line(raw)

                label = self.labels2id[RawPreprocessor._get_target(line)[0]]

                labels[line_i] = label
                labels_counter[label] += 1

                with open(self.out_dir / f"{line_i}.json", "w") as out_file:
                    json.dump(line, out_file)

            with open(self.label_info_path, "wb") as out_file:
                pickle.dump((labels_counter, labels), out_file)
            logger.info(f"Label information was dumped to {self.label_info_path}.")

        split_info = self._split_train_test(labels)

        return labels_counter, labels, split_info

    def _split_train_test(self, labels: np.ndarray):
        """Deterministic per-class stratified split (split_dataset.py:156-188)."""
        if self.split_info_path.exists():
            with open(self.split_info_path, "rb") as in_file:
                (train_indexes, train_labels, test_indexes, test_labels) = pickle.load(in_file)
            logger.info(f"Split information was loaded from {self.split_info_path}.")
        else:
            indexes = np.arange(len(labels))
            rng = np.random.default_rng(0)

            train_indexes, train_labels, test_indexes, test_labels = [], [], [], []
            for label_i in range(len(self.labels2id)):
                class_ids = indexes[labels == label_i]
                if len(class_ids) == 0:
                    continue
                perm = rng.permutation(class_ids)
                n_test = max(1, int(round(len(perm) * self.test_size))) if len(perm) > 1 else 0

                test_ids = perm[:n_test]
                train_ids = perm[n_test:]

                train_indexes.append(train_ids)
                train_labels.append(np.full(len(train_ids), label_i, dtype=labels.dtype))
                test_indexes.append(test_ids)
                test_labels.append(np.full(len(test_ids), label_i, dtype=labels.dtype))

            train_indexes = np.concatenate(train_indexes, axis=0)
            train_labels = np.concatenate(train_labels, axis=0)
            test_indexes = np.concatenate(test_indexes, axis=0)
            test_labels = np.concatenate(test_labels, axis=0)

            with open(self.split_info_path, "wb") as out_file:
                pickle.dump((train_indexes, train_labels, test_indexes, test_labels), out_file)
            logger.info(f"Split information was dumped to {self.split_info_path}.")

        assert len(train_indexes) == len(train_labels)
        assert len(test_indexes) == len(test_labels)

        return train_indexes, train_labels, test_indexes, test_labels
