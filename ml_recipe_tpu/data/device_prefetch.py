"""Double-buffered device prefetch: host->device staging off the step path.

The train loop used to assemble the global array for step N's batch (micro
split + ``make_global_array``, a blocking host->device copy) on the critical
path between dispatching step N-1 and step N — the device idled for the full
copy every step. :class:`DevicePrefetcher` moves that placement into a
background thread that keeps ``depth`` (default 2) placed batches in flight,
so the H2D copy of step k+1 overlaps the device compute of step k (the
train-side analogue of the predictor's transfer thread, and of the MPMD
compute/transfer overlap in PAPERS.md).

Guarantees the trainer's bit-identity test pins:

- ORDER: one worker thread, FIFO bounded queue — batches come out in exactly
  the order the source iterator yields them, placed by exactly the same
  ``place_fn`` the synchronous path runs. Same arrays, same step order, same
  trajectory.
- ERRORS: a worker failure is captured WITH its traceback and re-raised on
  the consumer thread as :class:`~ml_recipe_tpu.data.loader.DataLoaderWorkerError`
  (the loader-worker convention), so the stack that actually failed is never
  lost across the queue.
- DRAIN: ``close()`` (also the context-manager exit and generator close)
  stops the worker, unblocks it if it is parked on the full queue, and joins
  it with a timeout — a worker still alive after that gets its stack logged
  (it is the only clue to what it is wedged on) and, when no other exception
  is already propagating, raises.
- WATCHDOG: the consumer blocks in ``queue.get`` inside the trainer's armed
  step frame, so a wedged prefetch thread trips the step watchdog like any
  other stuck step — the all-thread stack dump includes this worker. The
  ``loader.prefetch`` fault site fires per staged batch for drills.
"""

from __future__ import annotations

import logging
import queue
import sys
import threading
import traceback
from typing import Any, Callable, Iterable, Iterator

from ..metrics import trace as trace_mod
from ..resilience.faults import fire as _fault
from .loader import DataLoaderWorkerError

logger = logging.getLogger(__name__)


class _WorkerFailure:
    __slots__ = ("exc", "tb")

    def __init__(self, exc: BaseException, tb: str):
        self.exc = exc
        self.tb = tb


class DevicePrefetcher:
    """Iterate ``place_fn(item)`` for each item of ``source``, with the
    placement running ``depth`` batches ahead on a background thread."""

    _DONE = object()

    def __init__(
        self,
        source: Iterable,
        place_fn: Callable[[Any], Any],
        *,
        depth: int = 2,
        join_timeout: float = 10.0,
        name: str = "device-prefetch",
    ):
        self._source = source
        self._place = place_fn
        self.depth = max(1, int(depth))
        self._join_timeout = join_timeout
        self._queue: queue.Queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._worker, name=name, daemon=True)
        self._started = False
        self._closed = False

    # -- worker ----------------------------------------------------------------

    def _worker(self) -> None:
        try:
            for item in self._source:
                if self._stop.is_set():
                    return
                _fault("loader.prefetch")
                # span covers placement AND the park on a full queue, so a
                # Perfetto view of the prefetch track shows backpressure
                # (device ahead of host) that the consumer-side spans can't
                with trace_mod.span("prefetch_stage", cat="train"):
                    payload = (self._place(item),)
                    while not self._stop.is_set():
                        try:
                            self._queue.put(payload, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                if self._stop.is_set():
                    return
        except BaseException as exc:  # noqa: BLE001 - re-raised on consumer
            # capture the traceback HERE: the exception crosses the queue and
            # is re-raised on the consumer thread, where this stack is gone
            tb = traceback.format_exc()
            logger.error(f"Device-prefetch worker failed:\n{tb}")
            self._put_final(_WorkerFailure(exc, tb))
        else:
            self._put_final(self._DONE)

    def _put_final(self, token) -> None:
        while not self._stop.is_set():
            try:
                self._queue.put(token, timeout=0.1)
                return
            except queue.Full:
                continue

    # -- consumer --------------------------------------------------------------

    def __iter__(self) -> Iterator:
        if self._closed or self._started:
            # single-use by design (one worker, one pass over the source):
            # a second iteration would block forever in queue.get with no
            # producer — fail fast instead (build a new prefetcher per epoch)
            raise RuntimeError(
                "DevicePrefetcher is single-use; construct a new instance "
                "for each pass over the source iterator"
            )
        self._started = True
        self._thread.start()
        try:
            while True:
                got = self._queue.get()
                if got is self._DONE:
                    return
                if isinstance(got, _WorkerFailure):
                    raise DataLoaderWorkerError(
                        f"device-prefetch worker failed: {got.exc!r}\n"
                        f"--- worker traceback ---\n{got.tb}"
                    ) from got.exc
                yield got[0]
        finally:
            self.close()

    def close(self) -> None:
        """Stop the worker and join it. Idempotent; safe mid-exception (a
        still-alive worker is then only warned about — the propagating error
        is the story, not the shutdown complaint)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        while True:  # unblock a worker parked on the full queue
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        if not self._started:
            return
        self._thread.join(timeout=self._join_timeout)
        if not self._thread.is_alive():
            return
        frame = sys._current_frames().get(self._thread.ident)
        stack = (
            "".join(traceback.format_stack(frame))
            if frame is not None
            else "<no frame available>"
        )
        logger.warning(
            f"Prefetch thread {self._thread.name!r} still alive "
            f"{self._join_timeout:g}s after close; its stack:\n{stack}"
        )
        if sys.exc_info()[0] is None:
            raise DataLoaderWorkerError(
                f"device-prefetch thread {self._thread.name!r} failed to "
                f"stop within {self._join_timeout:g}s (stack logged above)"
            )

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()
