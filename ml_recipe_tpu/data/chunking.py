"""Long-document chunking engine.

Parity targets (semantics, not structure — the reference duplicates this logic
between its train and validation datasets; here it lives once):

- HTML-tag dropping + word<->token offset maps (o2t/t2o):
  reference split_dataset.py:246-265 (``_drop_tags_and_encode``).
- Fixed-stride sliding-window chunking: split_dataset.py:267-322
  (``_split_doc`` — windows of ``max_seq_len - len(q) - 3`` stepping
  ``doc_stride``).
- Sentence-boundary packing with a rolling window: split_dataset.py:324-465
  (``_split_doc_by_sentence``), using our first-party sentence splitter
  instead of nltk punkt.
- Truncation of over-long sentence chunks: split_dataset.py:430-442 (the
  answer-window slice here is computed relative to the slice, fixing the
  reference's absolute-index arithmetic).

Each chunker returns every chunk of the document as :class:`ChunkRecord`;
the train dataset weighted-samples one (split_dataset.py:302-306,423-426),
the validation dataset keeps all (validation_dataset.py:138-168).

This is host-side Python by design: chunk geometry is data-dependent and
belongs outside jit; the TPU sees only fixed-shape padded batches.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, replace
from typing import Callable, List, Optional, Sequence, Tuple

_TAG_RE = re.compile(r"<.+>")

# Answer-bearing chunks weighted 1, answerless 1e-3 (split_dataset.py:221).
LABEL2WEIGHT = {"yes": 1.0, "no": 1.0, "short": 1.0, "long": 1.0, "unknown": 1e-3}


@dataclass
class ChunkRecord:
    """One candidate chunk of a document, with provenance."""

    token_ids: List[int]  # document-side tokens only (no [CLS]/question/[SEP])
    start: int  # answer start index in the FINAL input (question offset applied), or -1
    end: int
    label: str
    doc_start: int  # chunk bounds in document-token coordinates
    doc_end: int
    n_sents: int = 0


def drop_tags_and_encode(
    tokenizer, text: str, *, history_len: int = 0, start: int = -1
) -> Tuple[List[int], List[int], List[int], int, int]:
    """Tokenize whitespace-split words, skipping ``<...>`` HTML-tag words.

    Returns ``(token_ids, o2t, t2o, new_history_len, last_word_i)`` where
    ``o2t[word_i]`` is the token index at which word ``word_i`` begins (tag
    words map to the next real token) and ``t2o[tok_i]`` is the word index a
    token came from. ``history_len``/``start`` continue the numbering across
    sentence-by-sentence calls.
    """
    words = text.split()

    o2t: List[int] = []
    t2o: List[int] = []

    token_ids: List[int] = []
    word_i = start
    for word_i, word in enumerate(words, start=start + 1):
        o2t.append(len(token_ids) + history_len)
        if _TAG_RE.match(word):
            continue

        for token in tokenizer.encode(word):
            t2o.append(word_i)
            token_ids.append(token)

    return token_ids, o2t, t2o, history_len + len(token_ids), word_i


def encode_document(tokenizer, text: str):
    """Whole-document encoding with offset maps.

    ``o2t`` gets a trailing SENTINEL entry ``o2t[n_words] == n_tokens``:
    answer spans use exclusive word ends, so a span ending at the document's
    last word maps through ``o2t[len(words)]``. (The reference indexes o2t
    unguarded, split_dataset.py:274-275 — it crashes on a corpus line whose
    annotated long answer is the final candidate; found by the real-schema
    fixtures, tests/test_nq_fixtures.py.)"""
    token_ids, o2t, t2o, _, _ = drop_tags_and_encode(tokenizer, text)
    o2t.append(len(token_ids))
    return token_ids, o2t, t2o


def encode_document_by_sentences(
    tokenizer, text: str, sentence_splitter: Callable[[str], List[str]]
):
    """Per-sentence encoding with document-global offset maps."""
    sentences = sentence_splitter(text)

    t_sens: List[List[int]] = []
    o2t: List[int] = []
    t2o: List[int] = []

    start = -1
    history = 0
    for sen in sentences:
        sen_ids, o2t_, t2o_, history, start = drop_tags_and_encode(
            tokenizer, sen, history_len=history, start=start
        )
        t_sens.append(sen_ids)
        o2t.extend(o2t_)
        t2o.extend(t2o_)

    # same end-of-document sentinel as encode_document: exclusive span ends
    # at the last word map to one past the last token
    o2t.append(history)
    return t_sens, o2t, t2o


def _label_for_window(
    doc_start: int,
    doc_end: int,
    start_position: int,
    end_position: int,
    class_label: str,
    question_offset: int,
) -> Tuple[int, int, str]:
    """Answer indices within one chunk window, 'unknown' if not contained."""
    if not (doc_start <= start_position and end_position <= doc_end):
        return -1, -1, "unknown"
    return (
        start_position - doc_start + question_offset,
        end_position - doc_start + question_offset,
        class_label,
    )


def window_chunks(
    encoded_text: Sequence[int],
    target: Tuple[str, int, int],
    *,
    question_len: int,
    max_seq_len: int,
    doc_stride: int,
    first_only: bool = False,
) -> List[ChunkRecord]:
    """Fixed-stride sliding windows (split_dataset.py:287-306 semantics)."""
    class_label, start_position, end_position = target
    document_len = max_seq_len - question_len - 3  # [CLS], [SEP], [SEP]
    question_offset = question_len + 2

    records: List[ChunkRecord] = []
    for doc_start in range(0, max(len(encoded_text), 1), doc_stride):
        doc_end = doc_start + document_len
        start, end, label = _label_for_window(
            doc_start, doc_end, start_position, end_position, class_label, question_offset
        )
        records.append(
            ChunkRecord(
                token_ids=list(encoded_text[doc_start:doc_end]),
                start=start,
                end=end,
                label=label,
                doc_start=doc_start,
                doc_end=doc_end,
            )
        )
        if first_only:
            break

    return records


def sentence_chunks(
    t_sens: Sequence[Sequence[int]],
    target: Tuple[str, int, int],
    *,
    question_len: int,
    max_seq_len: int,
) -> List[ChunkRecord]:
    """Sentence-packed rolling-window chunks (split_dataset.py:374-412).

    A chunk is emitted every time appending the next sentence would overflow
    the window; the window then drops sentences from the front until the new
    sentence fits. A final tail chunk always closes the document.
    """
    class_label, start_position, end_position = target
    document_len = max_seq_len - question_len - 3
    question_offset = question_len + 2

    records: List[ChunkRecord] = []

    doc_start = 0
    doc_end = 0
    window: List[Sequence[int]] = []

    def emit(n_sents: int) -> None:
        start, end, label = _label_for_window(
            doc_start, doc_end, start_position, end_position, class_label, question_offset
        )
        records.append(
            ChunkRecord(
                token_ids=[t for sen in window for t in sen],
                start=start,
                end=end,
                label=label,
                doc_start=doc_start,
                doc_end=doc_end,
                n_sents=n_sents,
            )
        )

    for sen_ids in t_sens:
        assert doc_end - doc_start >= 0

        if doc_end - doc_start + len(sen_ids) > document_len:
            while window and (doc_end - doc_start + len(sen_ids) > document_len):
                emit(len(window))
                dropped = window.pop(0)
                doc_start += len(dropped)

        doc_end += len(sen_ids)
        window.append(sen_ids)

    emit(len(window))  # tail

    return records


def truncate_record(rec: ChunkRecord, *, question_len: int, max_seq_len: int) -> ChunkRecord:
    """Cut an over-long sentence chunk down to the window (split_dataset.py:430-442).

    If the answer lies inside the first ``document_len`` tokens the chunk is
    simply cut; otherwise the cut window is re-anchored at the answer start
    and the span re-indexed relative to the slice.
    """
    document_len = max_seq_len - question_len - 3
    question_offset = question_len + 2

    if len(rec.token_ids) <= document_len:
        return rec

    start_ = rec.start - question_offset
    end_ = rec.end - question_offset

    if start_ < document_len and end_ < document_len:
        return replace(rec, token_ids=rec.token_ids[:document_len])

    token_ids = rec.token_ids[start_:start_ + document_len]
    new_end = min(end_ - start_, len(token_ids))
    return replace(
        rec,
        token_ids=token_ids,
        start=question_offset,
        end=new_end + question_offset,
    )


def assemble_input_ids(
    cls_id: int, sep_id: int, encoded_question: Sequence[int], rec: ChunkRecord
) -> List[int]:
    """``[CLS] question [SEP] chunk [SEP]`` (split_dataset.py:309-311)."""
    return [cls_id, *encoded_question, sep_id, *rec.token_ids, sep_id]


def label_safe_cut(
    length: int,
    span: Optional[Tuple[int, int]],
    hole: int,
    min_fragment: int,
) -> Optional[int]:
    """Token-boundary cut point for splitting a ``length``-token chunk so
    its head fragment fills a ``hole``-token residual gap of an open pack
    row (data/packing.py's splitting packer), or ``None`` when no legal cut
    exists.

    A cut at ``c`` makes fragments ``[0, c)`` and ``[c, length)``. Legal
    means: both fragments are at least ``min_fragment`` tokens (no
    degenerate one-token segments), the head fits the hole (``c <= hole``),
    and the cut NEVER lands strictly inside the gold answer span ``span``
    (inclusive ``(start, end)`` token indices into the chunk) — a bisected
    span would leave NO fragment containing the whole answer, so neither
    could carry the labels. The nominal cut is the hole-filling maximum
    ``min(hole, length - min_fragment)``; when that would bisect the span,
    the cut retreats to the span start (the span moves wholly into the
    tail — the nominal cut is already the LARGEST legal cut, so past the
    span end is never an option), and when even that violates the
    min_fragment floor there is no legal cut. Pure arithmetic over
    ``(length, span, hole)`` — the property that lets every host derive
    identical split plans from the shared length oracle.
    """
    min_fragment = max(1, int(min_fragment))
    cut = min(int(hole), int(length) - min_fragment)
    if cut < min_fragment:
        return None
    if span is not None:
        start, end = int(span[0]), int(span[1])
        if 0 <= start <= end < length and start < cut <= end:
            # nominal cut bisects the span: retreat to its start so the
            # whole span lands in the tail fragment
            if start < min_fragment:
                return None
            cut = start
    return cut


def chunk_sampling_weights(records: Sequence[ChunkRecord]):
    import numpy as np

    weights = np.asarray([LABEL2WEIGHT[r.label] for r in records], dtype=np.float64)
    return weights / weights.sum()


def pick_eval_chunk(records: Sequence[ChunkRecord], class_label: str) -> int:
    """Deterministic pick for test mode: first chunk carrying the true label
    (split_dataset.py:417-421); falls back to the last chunk."""
    idx = len(records) - 1
    for i, rec in enumerate(records):
        if rec.label == class_label:
            return i
    return idx
