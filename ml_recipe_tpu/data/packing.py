"""Sequence packing for the training/eval input path.

PR 4's length-bucketed batching pads each item only to its bucket, cutting
padding waste 45.7% -> 12.1% on the synthetic NQ mix — but every remaining
pad row still burns attention/FFN FLOPs, and each occupied bucket costs its
own compiled program. This module removes the residual waste the way
large-scale pretraining stacks do (RoBERTa FULL-SENTENCES packing; T5/PaLM
example packing with segment masks): CONCATENATE short chunks into one
fixed ``max_seq_len`` row, so ~every token is a real token and the train
step compiles exactly ONE ``(rows, max_seq_len)`` program.

The pieces:

- :class:`SequencePacker` — greedy first-fit binning of tokenized chunks
  into rows, walking the SAME deterministic weighted/shuffled epoch order
  the samplers draw (packing changes row composition, never item order);
  ``splitting='fill'`` (``--pack_splitting``) additionally splits a chunk
  that fits no open row at a label-safe token boundary
  (chunking.label_safe_cut) and drops the head :class:`ChunkFragment` into
  the largest residual hole — the only path below the ~1.6% waste floor
  that quantized chunk-length mixes impose on ANY non-splitting packer;
- :func:`collate_packed` — one packed batch: ``input_ids`` /
  ``attention_mask`` / ``token_type_ids`` planes plus ``segment_ids``
  (1..S per segment, 0 on pad — also the attention kernels' block-diagonal
  mask operand), per-segment ``position_ids`` (reset to 0 at every segment
  boundary), ``segment_starts`` (each segment's [CLS] row index, for the
  per-segment pooled heads) and per-SEGMENT labels ``[rows, S]`` with a
  ``segment_mask`` validity plane — the scatter map back to original chunk
  indices is simply row-major segment order over ``segment_mask``;
- :class:`PackedDataLoader` — the train/eval loader, mirroring
  ``BucketedDataLoader``'s reader pipeline, epoch-order preservation,
  drop-last/pad-last discipline and token accounting
  (``epoch_stats['packing_efficiency']`` = real tokens / physical tokens).

Attention correctness is the ops layer's job: ``segment_ids`` rides the
kernels' mask operand and every regime (fused / q-blocked / streaming,
forward AND backward) applies the block-diagonal permission grid
``q_seg == k_seg != 0`` (ops/flash_attention.py, ops/flash_streaming.py).

Multi-host note: packing (like bucketing) is content-dependent — row
composition depends on chunk lengths, which every host must agree on for
step shapes to stay in lockstep. The multi-host path solves this with the
SHARED LENGTH ORACLE (:func:`oracle_read` / :func:`oracle_epoch_lengths`):
item reads pin the dataset's chunk-sampling RNG to a pure function of
``(ORACLE_SEED, index)``, so every host materializes bit-identical items
and derives the SAME per-epoch pack/bucket plan from the deterministic
epoch ordering — each host then collates only its contiguous row slice of
every planned global batch. No coordination traffic; the plan is pure
function of (seed, lengths).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from .chunking import label_safe_cut
from .loader import _read_with_retry

logger = logging.getLogger(__name__)

# Seed of the shared length oracle: item reads under the oracle pin the
# dataset's chunk-sampling RNG to default_rng([ORACLE_SEED, index]), making
# every read a pure function of the index — the property that lets N hosts
# agree on every item length (and therefore on the whole epoch's bucket/
# pack plan) without exchanging a byte.
ORACLE_SEED = 0x0AC1E

# dataset.rng swap is process-global state; serialize oracle reads of
# rng-carrying datasets (rng-less datasets — the common deterministic
# corpora — read fully parallel)
_ORACLE_LOCK = threading.Lock()


def oracle_read(dataset, index: int, *, retries: int = 3, epoch: int = 0):
    """One deterministic item read: when the dataset carries a chunk-
    sampling ``rng``, it is swapped for a throwaway seeded by
    ``(ORACLE_SEED, epoch, index)`` for the duration — the read becomes a
    pure function of ``(epoch, index)``, identical on every host and on
    every repeat (the length pass and the later collate pass see the SAME
    item), while still drawing FRESH chunks each epoch exactly like the
    single-host live-rng path does. The training draw stream is
    untouched."""
    if getattr(dataset, "rng", None) is None:
        return _read_with_retry(dataset, int(index), retries=retries)
    with _ORACLE_LOCK:
        saved = dataset.rng
        dataset.rng = np.random.default_rng(
            np.random.SeedSequence([ORACLE_SEED, int(epoch), int(index)])
        )
        try:
            return _read_with_retry(dataset, int(index), retries=retries)
        finally:
            dataset.rng = saved


def _oracle_epoch_key(dataset, epoch: int) -> int:
    """Cache-key epoch component: deterministic (rng-less) datasets return
    the same item every epoch, so their lengths are cached ONCE across the
    whole run; stochastic-chunk datasets draw per-epoch under the oracle
    and their lengths are cached per epoch."""
    return int(epoch) if getattr(dataset, "rng", None) is not None else 0


def _item_meta(item) -> Tuple[int, int, int]:
    """The cached planning meta of one item: ``(length, start_id, end_id)``
    — everything the pack/bucket planners (including every split decision
    of the splitting packer, which must steer cuts around the gold span)
    need, without holding the item itself."""
    return (
        len(item.input_ids),
        int(getattr(item, "start_id", -1)),
        int(getattr(item, "end_id", -1)),
    )


def _meta_span(meta) -> Optional[Tuple[int, int]]:
    """Span tuple of one cached meta (see :func:`_item_span`)."""
    _length, start, end = meta
    if start < 0 or end < start:
        return None
    return start, end


def oracle_epoch_meta(dataset, indices, *, cache: Dict[tuple, tuple],
                      n_jobs: int, read_retries: int,
                      epoch: int = 0) -> List[tuple]:
    """Item metas ``(length, start_id, end_id)`` for ``indices`` under the
    shared oracle, reading each UNIQUE ``(epoch, index)`` at most once
    (``cache`` persists across epochs and is EXACT here — oracle reads are
    reproducible, unlike the planning-only estimates of
    :func:`epoch_item_lengths`). The span rides along because the splitting
    packer's cut points are span-dependent: a length-only plan could not
    agree across hosts on WHERE a chunk splits.

    Cost model: deterministic (rng-less) corpora read fully parallel and
    their metas are cached ONCE for the whole run; stochastic-chunk
    (rng-carrying) datasets re-draw per epoch AND serialize on the oracle
    lock (``dataset.rng`` is shared mutable state — there is no parallel
    read under a pinned generator), so every host pays one serial
    materialization pass over the epoch per epoch. That is the price of
    host-agreed plans with live chunk re-sampling; corpora where it bites
    should pre-tokenize (drop the rng) or accept frozen epoch-0 draws."""
    ek = _oracle_epoch_key(dataset, epoch)
    missing = sorted({int(i) for i in indices if (ek, int(i)) not in cache})
    if missing:
        with ThreadPoolExecutor(max_workers=max(1, n_jobs)) as pool:
            for idx, item in zip(
                missing,
                pool.map(
                    lambda i: oracle_read(
                        dataset, i, retries=read_retries, epoch=ek
                    ),
                    missing,
                ),
            ):
                cache[(ek, idx)] = _item_meta(item)
    return [cache[(ek, int(i))] for i in indices]


def oracle_epoch_lengths(dataset, indices, *, cache: Dict[tuple, tuple],
                         n_jobs: int, read_retries: int,
                         epoch: int = 0) -> List[int]:
    """Item lengths under the shared oracle — :func:`oracle_epoch_meta`
    with only the length column (what the bucket planner consumes)."""
    return [
        meta[0]
        for meta in oracle_epoch_meta(
            dataset, indices, cache=cache, n_jobs=n_jobs,
            read_retries=read_retries, epoch=epoch,
        )
    ]

# Per-row segment cap: keeps the per-segment label planes ([rows, S]) and
# the model's per-segment head outputs at one static shape. 8 comfortably
# covers the NQ chunk mix at max_seq_len 384-512 (min chunk ~ question+CLS/
# SEP overhead ~ 35 tokens only for degenerate documents).
DEFAULT_MAX_SEGMENTS = 8

# Bounded open-row window of the greedy first-fit packer: more open rows =
# tighter packing (more chances to fill a gap) at the cost of a longer
# emission delay. Measured on the synthetic NQ mix (seq 512, drop-last
# accounting): window 8 -> 3.16% waste, 16 -> 2.70%, 32 -> 2.40%, 64 ->
# 2.44% — saturation at 32. (The residual is the MIX's floor, not the
# packer's: its 463-token chunks leave a 49-token hole no chunk can fill,
# ~1.6% for any non-splitting packer; on continuous NQ-like length mixes
# the same packer lands under 2%, pinned in tests/test_packing.py.)
DEFAULT_OPEN_ROWS = 32


def parse_sequence_packing(spec) -> bool:
    """Flag domain of ``--sequence_packing``: truthy strings/bools -> on,
    ``off``/``none``/``0``/``false`` (or None/False) -> off."""
    if spec is None or spec is False:
        return False
    if spec is True:
        return True
    s = str(spec).strip().lower()
    return s not in ("off", "none", "0", "false", "")


# Minimum fragment size of the splitting packer (--pack_min_fragment): no
# fragment — head or tail — goes below this many tokens, so splitting never
# manufactures degenerate few-token segments (which would burn a segment
# slot and a pooled-head row for ~no context). 32 clears the synthetic NQ
# mix's ~49-token holes while keeping every fragment a meaningful window.
DEFAULT_MIN_FRAGMENT = 32


def parse_pack_splitting(spec) -> str:
    """Flag domain of ``--pack_splitting``: ``off`` (default — the
    non-splitting packer, bit-identical to the pre-splitting code path) or
    ``fill`` (split pending chunks at label-safe token boundaries to fill
    residual holes). Truthy bools/strings alias ``fill``."""
    if spec is None or spec is False:
        return "off"
    if spec is True:
        return "fill"
    s = str(spec).strip().lower()
    if s in ("off", "none", "0", "false", ""):
        return "off"
    if s in ("fill", "on", "1", "true", "yes"):
        return "fill"
    raise ValueError(f"--pack_splitting must be off|fill, got {spec!r}")


@dataclasses.dataclass
class ChunkFragment:
    """One fragment of a split chunk, carried through pack rows in place of
    the whole item. ``item`` is whatever payload the packer was given (a
    DatasetItem/ChunkItem on the live path, an ``(index, length)`` pair on
    the oracle plan, ``None`` in step simulations); ``offset``/``length``
    slice the parent chunk's token stream, ``(chunk_id, index, count)`` are
    the re-merge provenance (``count`` is stamped once the whole chunk is
    placed), and ``keep_labels`` marks the ONE fragment that carries the
    parent's labels — the one containing the gold span (the head for
    spanless chunks); siblings collate with ``segment_mask`` 0, which the
    packed loss rewrites to each head's ignore-index, so a split chunk is
    never double-counted."""

    item: Any
    chunk_id: int
    offset: int
    length: int
    index: int
    count: int = 0
    keep_labels: bool = False
    chunk_len: int = 0


def _entry_tokens(entry) -> int:
    """Token count of one pack-row entry (whole item or fragment)."""
    if isinstance(entry, ChunkFragment):
        return entry.length
    return len(entry.input_ids)


def _entry_is_example(entry) -> bool:
    """Does this entry count as a real example (label carrier)? Whole items
    always; of a split chunk, only the ``keep_labels`` fragment."""
    return entry.keep_labels if isinstance(entry, ChunkFragment) else True


def _item_span(item) -> Optional[Tuple[int, int]]:
    """Gold-span token indices of an item (inclusive, into its input_ids),
    or None when spanless/unknown — what label-safe cuts steer around."""
    start = int(getattr(item, "start_id", -1))
    end = int(getattr(item, "end_id", -1))
    if start < 0 or end < start:
        return None
    return start, end


# LR-schedule planning reads item LENGTHS, which means materializing items
# (chunk assembly + tokenization). Bound that pre-training pass: past this
# many items the planners simulate on the epoch ordering's prefix and scale
# the step count — the length POPULATION is what drives packing/bucketing
# density, and a 4k prefix of a shuffled ordering samples it tightly.
PLAN_SAMPLE_ITEMS = 4096


def epoch_item_meta(dataset, sampler, epoch, *, cache: Dict[int, tuple],
                    n_jobs: int, read_retries: int,
                    max_items: Optional[int] = None,
                    oracle: bool = False) -> List[tuple]:
    """Item metas ``(length, start_id, end_id)`` in one epoch's order
    (truncated to ``max_items`` when given), reading each UNIQUE index at
    most once (``cache`` persists across epochs — for stochastic-chunk
    datasets the cached meta is one draw, an estimate by construction). The
    dataset's chunk-sampling RNG, when it has one, is swapped for a
    throwaway during the reads so PLANNING never perturbs the training draw
    stream. Shared by the packed and bucketed loaders' LR-schedule step
    planning. ``oracle=True`` switches the reads to the shared length
    oracle (per-index pinned RNG): exact and host-invariant — what
    multi-host planning must use, since a host-divergent step estimate
    would diverge the LR schedule itself."""
    indices = [int(i) for i in sampler.epoch_indices(epoch)]
    if max_items is not None:
        indices = indices[:max_items]
    if oracle:
        return oracle_epoch_meta(
            dataset, indices, cache=cache, n_jobs=n_jobs,
            read_retries=read_retries, epoch=epoch,
        )
    missing = sorted({i for i in indices if i not in cache})
    if missing:
        saved_rng = getattr(dataset, "rng", None)
        if saved_rng is not None:
            dataset.rng = np.random.default_rng(0)
        try:
            with ThreadPoolExecutor(max_workers=max(1, n_jobs)) as pool:
                for idx, item in zip(
                    missing,
                    pool.map(
                        lambda i: _read_with_retry(
                            dataset, i, retries=read_retries
                        ),
                        missing,
                    ),
                ):
                    cache[idx] = _item_meta(item)
        finally:
            if saved_rng is not None:
                dataset.rng = saved_rng
    return [cache[i] for i in indices]


def epoch_item_lengths(dataset, sampler, epoch, *, cache: Dict[int, tuple],
                       n_jobs: int, read_retries: int,
                       max_items: Optional[int] = None,
                       oracle: bool = False) -> List[int]:
    """Item lengths in one epoch's order — :func:`epoch_item_meta` with
    only the length column."""
    return [
        meta[0]
        for meta in epoch_item_meta(
            dataset, sampler, epoch, cache=cache, n_jobs=n_jobs,
            read_retries=read_retries, max_items=max_items, oracle=oracle,
        )
    ]


def plan_scaled_count(dataset, sampler, epoch, *, cache: Dict[int, tuple],
                      n_jobs: int, read_retries: int, simulate,
                      oracle: bool = False, meta: bool = False) -> int:
    """Shared LR-schedule planning skeleton of the packed and bucketed
    loaders: read the epoch's item lengths (prefix-bounded by
    ``PLAN_SAMPLE_ITEMS``), run the loader-specific ``simulate(lengths) ->
    count``, and scale the count back to the full epoch when only a prefix
    was read. ``meta=True`` hands ``simulate`` the full ``(length,
    start_id, end_id)`` metas instead — the splitting packer's simulation
    needs the spans to replay its label-safe cut decisions exactly.
    Loader-specific tail handling (pad_last flushes, rows-per-batch
    division) stays with the caller — it must NOT be prefix-scaled."""
    n_total = len(sampler.epoch_indices(epoch))
    metas = epoch_item_meta(
        dataset, sampler, epoch, cache=cache, n_jobs=n_jobs,
        read_retries=read_retries, max_items=PLAN_SAMPLE_ITEMS,
        oracle=oracle,
    )
    count = simulate(metas if meta else [m[0] for m in metas])
    if metas and n_total > len(metas):
        count = int(round(count * n_total / len(metas)))
    return count


class SequencePacker:
    """Greedy first-fit packer: items arrive in epoch order, each is placed
    into the FIRST open row with room (and a free segment slot); when none
    fits and the open-row window is full, the FULLEST open row is emitted
    (ties to the oldest) — finalizing the best-packed row keeps the
    emptier ones around to catch fillers, measured 5.2% -> 3.5% waste at
    window 8 on the synthetic NQ mix vs emitting the oldest. Rows that
    fill exactly (or hit ``max_segments``) close eagerly. Pure function of
    the item sequence — deterministic under the deterministic epoch
    orderings the samplers draw.

    ``splitting='fill'`` adds the hole-filling pass that breaks the
    non-splitting packer's ~1.6% floor on quantized length mixes: an item
    that fits NO open row whole is split at a label-safe token boundary
    (:func:`ml_recipe_tpu.data.chunking.label_safe_cut` — the cut never
    bisects the gold ``span``), its head :class:`ChunkFragment` drops into
    the open row with the LARGEST residual hole, and the tail re-enters the
    same placement walk (it may fill another hole, split again, or open a
    new row). Fragments are ordinary segments downstream; only the
    span-bearing one carries labels. Still a pure function of the
    ``(item, length, span)`` sequence, so simulations and every oracle
    host replay the identical plan — and with ``splitting='off'`` (the
    default) the code path is EXACTLY the pre-splitting packer."""

    def __init__(self, max_seq_len: int, *,
                 max_segments: int = DEFAULT_MAX_SEGMENTS,
                 open_rows: int = DEFAULT_OPEN_ROWS,
                 splitting: str = "off",
                 min_fragment: int = DEFAULT_MIN_FRAGMENT):
        self.max_seq_len = int(max_seq_len)
        self.max_segments = max(1, int(max_segments))
        self.open_rows = max(1, int(open_rows))
        self.splitting = parse_pack_splitting(splitting)
        self.min_fragment = max(1, int(min_fragment))
        self.split_count = 0  # cuts performed (fragments created - chunks)
        self._open: List[tuple] = []  # (items, used_tokens)
        self._next_chunk_id = 0
        self._placing: List[ChunkFragment] = []  # fragments of the in-flight add

    def add(self, item, length: int, span=None) -> List[list]:
        """Place one item; returns the (possibly empty) list of COMPLETED
        rows this placement closed, each a list of entries (items and/or
        :class:`ChunkFragment`\\ s) in row order. ``span`` — the item's gold
        answer ``(start, end)`` token indices (or None) — only steers the
        splitting packer's cut points; the non-splitting path ignores it."""
        length = int(length)
        if length > self.max_seq_len:
            raise ValueError(
                f"item of length {length} exceeds max_seq_len "
                f"{self.max_seq_len} (the collate would reject it too)"
            )
        done: List[list] = []
        self._place(item, length, self._norm_span(span, length), done)
        if self._placing:
            # the chunk is now fully placed: stamp the final fragment count
            # on every fragment (re-merge needs to know when a chunk is
            # complete, and rows may be emitted out of placement order)
            for frag in self._placing:
                frag.count = len(self._placing)
            self._placing = []
        return done

    @staticmethod
    def _norm_span(span, length: int):
        if span is None:
            return None
        start, end = int(span[0]), int(span[1])
        if not (0 <= start <= end < length):
            return None
        return start, end

    def _place(self, entry, length: int, span, done: List[list]) -> None:
        """One placement step (whole-entry first-fit, then hole-filling
        split, then forced-emit + new row) — with ``splitting='off'`` this
        body is the historical ``add`` verbatim."""
        for i, (items, used) in enumerate(self._open):
            if used + length <= self.max_seq_len and len(items) < self.max_segments:
                items.append(entry)
                used += length
                if used == self.max_seq_len or len(items) == self.max_segments:
                    done.append(items)
                    del self._open[i]
                else:
                    self._open[i] = (items, used)
                return
        if (
            self.splitting == "fill"
            and length >= 2 * self.min_fragment
            and self._split_place(entry, length, span, done)
        ):
            return
        if len(self._open) >= self.open_rows:
            fullest = max(
                range(len(self._open)), key=lambda i: self._open[i][1]
            )
            done.append(self._open.pop(fullest)[0])
        self._open.append(([entry], length))

    def _split_place(self, entry, length: int, span, done: List[list]) -> bool:
        """Try to split ``entry`` so its head fragment fills an open row's
        residual hole; rows are tried largest-hole-first (ties to the
        oldest — determinism). Returns False when no row admits a legal
        label-safe cut (caller falls through to the non-splitting path)."""
        order = sorted(
            range(len(self._open)),
            key=lambda i: (self._open[i][1], i),
        )
        for i in order:
            items, used = self._open[i]
            hole = self.max_seq_len - used
            if hole < self.min_fragment or len(items) >= self.max_segments:
                continue
            cut = label_safe_cut(length, span, hole, self.min_fragment)
            if cut is None:
                continue
            head, tail, tail_span = self._cut(entry, length, span, cut)
            items.append(head)
            used += cut
            self.split_count += 1
            if used == self.max_seq_len or len(items) == self.max_segments:
                done.append(items)
                del self._open[i]
            else:
                self._open[i] = (items, used)
            # the tail re-enters the full placement walk: it may fit a row
            # whole, fill another hole (splitting again), or open a new row
            self._place(tail, tail.length, tail_span, done)
            return True
        return False

    def _cut(self, entry, length: int, span, cut: int):
        """Split ``entry`` at ``cut`` into (head, tail) fragments plus the
        tail-relative span. Labels follow the span: the fragment wholly
        containing it keeps them (head for spanless chunks); re-splitting a
        tail threads ``keep_labels`` through so exactly ONE fragment of the
        chunk ever carries them."""
        if isinstance(entry, ChunkFragment):
            parent, chunk_id = entry.item, entry.chunk_id
            base_offset, base_index = entry.offset, entry.index
            carried, chunk_len = entry.keep_labels, entry.chunk_len
            self._placing.remove(entry)
        else:
            parent, chunk_id = entry, self._next_chunk_id
            self._next_chunk_id += 1
            base_offset, base_index = 0, 0
            carried, chunk_len = True, length
        head_keeps = tail_keeps = False
        tail_span = None
        if carried:
            if span is None:
                head_keeps = True
            elif span[1] < cut:
                head_keeps = True
            else:  # label_safe_cut guarantees span[0] >= cut here
                tail_keeps = True
                tail_span = (span[0] - cut, span[1] - cut)
        head = ChunkFragment(
            item=parent, chunk_id=chunk_id, offset=base_offset, length=cut,
            index=base_index, keep_labels=head_keeps, chunk_len=chunk_len,
        )
        tail = ChunkFragment(
            item=parent, chunk_id=chunk_id, offset=base_offset + cut,
            length=length - cut, index=base_index + 1,
            keep_labels=tail_keeps, chunk_len=chunk_len,
        )
        self._placing.extend([head, tail])
        return head, tail, tail_span

    def flush(self) -> List[list]:
        """Emit every open row (epoch end), oldest first."""
        done = [items for items, _ in self._open]
        self._open = []
        return done


class PackedBatch(NamedTuple):
    """One collated packed batch: ``rows`` rows of ``seq`` tokens holding
    ``segments`` real segments (= original examples); pad rows (eval tail
    padding) repeat the last real row with ``segment_mask`` zeroed, so
    masked losses/metrics skip them without trimming. ``provenance`` (only
    populated under ``--pack_splitting fill``) carries the per-segment
    ``chunk_id`` / ``fragment_index`` / ``token_offset`` planes of the
    splitting packer — host-side metadata, never fed to the model."""

    inputs: dict
    labels: dict
    rows: int
    segments: int
    seq: int
    provenance: Optional[dict] = None


def collate_packed(row_items: Sequence[list], tokenizer, *,
                   max_seq_len: int, max_segments: int = DEFAULT_MAX_SEGMENTS,
                   with_labels: bool = True, with_provenance: bool = False):
    """Collate packed rows (lists of DatasetItem/ChunkItem and/or
    :class:`ChunkFragment`) into the packed batch schema.

    Inputs (all ``[rows, L]`` int32 except ``segment_starts``):
      - ``input_ids``: concatenated chunk ids, pad_token_id elsewhere;
      - ``attention_mask``: 1 on real tokens (= ``segment_ids > 0``);
      - ``token_type_ids``: the plain collate's BERT rule applied WITHIN
        each segment (0 through its first [SEP], 1 after); a fragment
        inherits its PARENT chunk's token-type slice, so the planes of a
        split chunk concatenate to exactly the unsplit chunk's;
      - ``segment_ids``: 1..S per segment, 0 on pad — the attention
        kernels' block-diagonal mask operand (fragments are ordinary
        segments under it);
      - ``position_ids``: 0..len(seg)-1 within each segment (position
        embeddings reset at every boundary), 0 on pad; a FRAGMENT's
        positions CONTINUE at its ``token_offset`` so every token keeps
        the position embedding it had in the unsplit chunk;
      - ``segment_starts`` ``[rows, S]``: each segment's first row index
        (the [CLS] for whole chunks and head fragments; gathered rows of
        absent segments are masked downstream).

    Labels (``[rows, S]``; ``with_labels=False`` skips them for pure
    inference): ``start_class``/``end_class`` are ROW-ABSOLUTE token
    indices (chunk-relative index + segment offset; -1 for spanless chunks
    AND absent segments — the span CE's ignore_index), ``start_reg``/
    ``end_reg``/``cls`` as in the plain collate, plus ``segment_mask``
    (1 = real segment) which the packed loss keys every mean on. Of a
    split chunk only the ``keep_labels`` fragment is a real segment — the
    label-safe cut guarantees it contains the whole gold span (rebased by
    its ``token_offset``); sibling fragments carry mask 0 and -1 spans, so
    the packed loss ignore-indexes them and the chunk is counted once.

    ``with_labels=False`` returns ``(inputs, segment_mask)`` where the
    mask marks every PRESENT segment (fragments included — inference
    consumers need all of them for the re-merge); ``with_provenance=True``
    appends a third element: the ``chunk_id`` / ``fragment_index`` /
    ``token_offset`` ``[rows, S]`` planes (-1/0/0 for whole chunks).
    """
    R, L, S = len(row_items), int(max_seq_len), int(max_segments)
    pad_id = tokenizer.pad_token_id
    sep_id = tokenizer.sep_token_id
    is_bert = getattr(tokenizer, "model_name", "bert") == "bert"

    input_ids = np.full((R, L), pad_id, dtype=np.int32)
    token_type_ids = np.zeros((R, L), dtype=np.int32)
    segment_ids = np.zeros((R, L), dtype=np.int32)
    position_ids = np.zeros((R, L), dtype=np.int32)
    segment_starts = np.zeros((R, S), dtype=np.int32)
    segment_mask = np.zeros((R, S), dtype=np.int32)

    start_class = np.full((R, S), -1, dtype=np.int32)
    end_class = np.full((R, S), -1, dtype=np.int32)
    start_reg = np.zeros((R, S), dtype=np.float32)
    end_reg = np.zeros((R, S), dtype=np.float32)
    cls = np.zeros((R, S), dtype=np.int32)

    if with_provenance:
        chunk_id = np.full((R, S), -1, dtype=np.int32)
        fragment_index = np.zeros((R, S), dtype=np.int32)
        token_offset = np.zeros((R, S), dtype=np.int32)

    for r, items in enumerate(row_items):
        assert len(items) <= S, (len(items), S)
        off = 0
        for s, entry in enumerate(items):
            frag = entry if isinstance(entry, ChunkFragment) else None
            item = frag.item if frag is not None else entry
            parent_row = item.input_ids
            if frag is not None:
                row = parent_row[frag.offset:frag.offset + frag.length]
                frag_off = frag.offset
            else:
                row = parent_row
                frag_off = 0
            n = len(row)
            assert off + n <= L, (
                f"packed row overflows max_seq_len {L} at segment {s} "
                f"(offset {off} + {n})"
            )
            input_ids[r, off:off + n] = row
            segment_ids[r, off:off + n] = s + 1
            position_ids[r, off:off + n] = frag_off + np.arange(
                n, dtype=np.int32
            )
            if is_bert:
                # segment 0 up to and including the first [SEP] WITHIN this
                # packed segment, 1 after (collate.py:42-51 semantics);
                # fragments slice the PARENT's plane so a split chunk's
                # token types concatenate to the unsplit chunk's
                sep_pos = (
                    parent_row.index(sep_id) if sep_id in parent_row
                    else len(parent_row) - 1
                )
                ones_from = max(sep_pos + 1 - frag_off, 0)
                if ones_from < n:
                    token_type_ids[r, off + ones_from:off + n] = 1
            segment_starts[r, s] = off
            is_example = frag is None or frag.keep_labels
            segment_mask[r, s] = 1 if (is_example or not with_labels) else 0
            if with_provenance:
                chunk_id[r, s] = frag.chunk_id if frag is not None else -1
                fragment_index[r, s] = frag.index if frag is not None else 0
                token_offset[r, s] = frag_off
            if with_labels and is_example:
                if item.start_id >= 0:
                    # the label-safe cut pins the whole span inside this
                    # fragment, so the rebased indices stay in [0, n)
                    start_class[r, s] = item.start_id - frag_off + off
                    end_class[r, s] = item.end_id - frag_off + off
                start_reg[r, s] = item.start_position
                end_reg[r, s] = item.end_position
                cls[r, s] = item.label_id
            off += n

    inputs = {
        "input_ids": input_ids,
        "attention_mask": (segment_ids > 0).astype(np.int32),
        "token_type_ids": token_type_ids,
        "segment_ids": segment_ids,
        "position_ids": position_ids,
        "segment_starts": segment_starts,
    }
    provenance = (
        {
            "chunk_id": chunk_id,
            "fragment_index": fragment_index,
            "token_offset": token_offset,
        }
        if with_provenance else None
    )
    if not with_labels:
        if with_provenance:
            return inputs, segment_mask, provenance
        return inputs, segment_mask
    labels = {
        "start_class": start_class,
        "end_class": end_class,
        "start_reg": start_reg,
        "end_reg": end_reg,
        "cls": cls,
        "segment_mask": segment_mask,
    }
    if with_provenance:
        return inputs, labels, provenance
    return inputs, labels


class PackedDataLoader:
    """Prefetching loader producing packed ``(rows, max_seq_len)`` batches.

    Walks ``sampler.epoch_indices(epoch)`` (the exact ordering the plain
    and bucketed loaders batch — weighted sampling preserved), reads items
    through the same retrying thread pool, bins them with the greedy
    first-fit :class:`SequencePacker`, and emits a :class:`PackedBatch`
    every ``rows_per_batch`` completed rows. Train mode (``pad_last=False``)
    drops the partial final BATCH of rows at epoch end (drop_last parity);
    eval mode (``pad_last=True``) pads it by repeating the last real row
    with ``segment_mask`` zeroed, so consumers need no trimming.

    Multi-host (``sampler.process_count > 1``): every host derives the SAME
    epoch pack plan from the shared length oracle (item lengths are a pure
    function of the index under :func:`oracle_read`) and collates only its
    contiguous ``rows_per_batch / process_count`` row slice of each planned
    global batch — step shapes stay in lockstep with zero coordination
    traffic. ``rows``/``segments`` on the emitted batches stay GLOBAL
    counts (what metric weighting and partial-batch trimming key on).
    """

    def __init__(
        self,
        dataset,
        sampler,
        tokenizer,
        *,
        max_seq_len: int,
        rows_per_batch: int,
        max_segments: int = DEFAULT_MAX_SEGMENTS,
        open_rows: int = DEFAULT_OPEN_ROWS,
        splitting: str = "off",
        min_fragment: int = DEFAULT_MIN_FRAGMENT,
        n_jobs: int = 4,
        read_window: Optional[int] = None,
        read_retries: int = 3,
        pad_last: bool = False,
    ):
        self.process_index = int(getattr(sampler, "process_index", 0))
        self.process_count = int(getattr(sampler, "process_count", 1))
        if self.process_count > 1 and rows_per_batch % self.process_count:
            raise ValueError(
                f"rows_per_batch {rows_per_batch} must divide over "
                f"{self.process_count} hosts (each host collates its "
                f"contiguous row slice of every planned global batch)"
            )
        self.dataset = dataset
        self.sampler = sampler
        self.tokenizer = tokenizer
        self.max_seq_len = int(max_seq_len)
        self.rows_per_batch = max(1, int(rows_per_batch))
        self.max_segments = max(1, int(max_segments))
        self.open_rows = max(1, int(open_rows))
        self.splitting = parse_pack_splitting(splitting)
        self.min_fragment = max(1, int(min_fragment))
        self.n_jobs = max(1, n_jobs)
        self.read_window = (
            int(read_window) if read_window is not None else self.n_jobs * 8
        )
        self.read_retries = max(0, read_retries)
        self.pad_last = pad_last
        self._epoch = 0
        self._last_stats: Optional[dict] = None
        # planning-meta cache: (length, start_id, end_id) tuples, keyed by
        # plain index (single-process planning) or (epoch_key, index)
        # (oracle reads) — see epoch_item_meta / oracle_epoch_meta
        self._len_cache: Dict[Any, tuple] = {}

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __len__(self) -> int:
        """UPPER-BOUND step estimate (each packed row holds >= 1 item, so an
        epoch takes at most ``len(sampler)`` steps). The LR schedule uses
        the much tighter :meth:`planned_epoch_steps` instead."""
        return len(self.sampler)

    # -- planning ---------------------------------------------------------

    def _make_packer(self) -> SequencePacker:
        """One packer configured exactly like the live epoch's — shared by
        iteration, the oracle plan, and the step simulation so all three
        replay the identical (split) plan."""
        return SequencePacker(
            self.max_seq_len, max_segments=self.max_segments,
            open_rows=self.open_rows, splitting=self.splitting,
            min_fragment=self.min_fragment,
        )

    def planned_epoch_steps(self, epoch: int) -> int:
        """Planned batch count of one epoch: simulate the packer over the
        epoch's item metas (one read per unique index, cached; on corpora
        past ``PLAN_SAMPLE_ITEMS`` the simulation runs on the epoch
        ordering's prefix and the row count is scaled — a whole extra
        tokenize pass before step 1 would dwarf what the plan buys). The
        simulation replays EVERY split decision — cuts are a pure function
        of ``(length, span, holes)`` and the metas carry the spans — so on
        a fully-read corpus planned == consumed even under
        ``--pack_splitting fill``. This is what the LR schedule should size
        against — ``len(self)`` is the pad-to-max upper bound and
        overshoots by ~the packing factor."""

        def simulate(metas):
            packer = self._make_packer()
            rows = 0
            for meta in metas:
                rows += len(packer.add(None, meta[0], _meta_span(meta)))
            return rows + len(packer.flush())

        rows = plan_scaled_count(
            self.dataset, self.sampler, epoch, cache=self._len_cache,
            n_jobs=self.n_jobs, read_retries=self.read_retries,
            simulate=simulate, oracle=self.process_count > 1, meta=True,
        )
        if self.pad_last:
            return -(-rows // self.rows_per_batch)
        return rows // self.rows_per_batch

    # -- iteration --------------------------------------------------------

    def _new_stats(self) -> dict:
        return {
            "real_tokens": 0,
            "supervised_tokens": 0,
            "physical_tokens": 0,
            "padmax_tokens": 0,
            "rows": 0,
            "batches": 0,
            "items": 0,
            "dropped_items": 0,
            "split_count": 0,
            "fragment_rows": 0,
            "fragment_size_hist": {},
        }

    @staticmethod
    def _count_fragments(rows: Sequence[list], stats: dict,
                         tokens_of=None) -> None:
        """Splitter accounting over emitted REAL rows: ``split_count`` cuts
        (one per non-head fragment), ``fragment_rows`` rows holding at
        least one fragment, and a power-of-two fragment-size histogram."""
        tokens_of = tokens_of or _entry_tokens
        for row in rows:
            has_frag = False
            for entry in row:
                if not isinstance(entry, ChunkFragment):
                    continue
                has_frag = True
                if entry.index > 0:
                    stats["split_count"] += 1
                n = tokens_of(entry)
                lo = 32
                while lo < n and lo < 512:
                    lo *= 2
                key = f"<={lo}" if n <= lo else f">{lo}"
                hist = stats["fragment_size_hist"]
                hist[key] = hist.get(key, 0) + 1
            if has_frag:
                stats["fragment_rows"] += 1

    def _emit(self, rows: List[list], stats: dict, *, real_rows=None):
        real = len(rows) if real_rows is None else int(real_rows)
        real_entries = [entry for row in rows[:real] for entry in row]
        splitting = self.splitting != "off"
        collated = collate_packed(
            rows, self.tokenizer, max_seq_len=self.max_seq_len,
            max_segments=self.max_segments, with_provenance=splitting,
        )
        inputs, labels = collated[0], collated[1]
        provenance = collated[2] if splitting else None
        if real < len(rows):
            # pad rows must not look like real examples
            labels["segment_mask"][real:] = 0
        segments = int(labels["segment_mask"].sum())
        # token accounting is per-ENTRY: a fragment contributes its own
        # slice (its siblings contribute theirs), so a split chunk's tokens
        # are counted exactly once; item counts follow the label carriers
        # (one per original example). real_tokens = PLACED (non-pad) tokens
        # — what padding_waste_pct complements; supervised_tokens excludes
        # sibling fragments' tokens, whose labels are ignore-indexed and
        # which (under block-diagonal attention) feed no gradient — the
        # honest numerator of the train-side packing_efficiency, so
        # hole-filling fragments can never inflate it
        n_examples = sum(1 for e in real_entries if _entry_is_example(e))
        stats["real_tokens"] += sum(_entry_tokens(e) for e in real_entries)
        stats["supervised_tokens"] += sum(
            _entry_tokens(e) for e in real_entries if _entry_is_example(e)
        )
        stats["physical_tokens"] += len(rows) * self.max_seq_len
        stats["padmax_tokens"] += n_examples * self.max_seq_len
        stats["rows"] += real
        stats["batches"] += 1
        stats["items"] += n_examples
        self._count_fragments(rows[:real], stats)
        return PackedBatch(
            inputs=inputs, labels=labels, rows=len(rows), segments=segments,
            seq=self.max_seq_len, provenance=provenance,
        )

    def _iter_oracle(self):
        """Multi-host epoch: plan globally from oracle metas, collate the
        local row slice. Every host computes the identical plan — split
        decisions included, since cuts are a pure function of the oracle's
        ``(length, span)`` metas — so per-step shapes, segment counts and
        stats agree bit-for-bit across hosts while each host only
        materializes 1/process_count of the rows for the device."""
        indices = [int(i) for i in self.sampler.epoch_indices(self._epoch)]
        self._last_stats = stats = self._new_stats()
        metas = oracle_epoch_meta(
            self.dataset, indices, cache=self._len_cache,
            n_jobs=self.n_jobs, read_retries=self.read_retries,
            epoch=self._epoch,
        )
        packer = self._make_packer()
        # each row: list of (index, length) pairs and/or ChunkFragments
        # whose .item is such a pair
        rows: List[list] = []
        for idx, meta in zip(indices, metas):
            rows.extend(packer.add((idx, meta[0]), meta[0], _meta_span(meta)))
        rows.extend(packer.flush())

        def entry_index(entry) -> int:
            return (entry.item if isinstance(entry, ChunkFragment)
                    else entry)[0]

        def entry_tokens(entry) -> int:
            if isinstance(entry, ChunkFragment):
                return entry.length
            return entry[1]

        rpb = self.rows_per_batch
        local_rows = rpb // self.process_count
        lo = self.process_index * local_rows
        ek = _oracle_epoch_key(self.dataset, self._epoch)

        batches = [
            (rows[b * rpb:(b + 1) * rpb], rpb)
            for b in range(len(rows) // rpb)
        ]
        tail = rows[(len(rows) // rpb) * rpb:]
        if tail:
            if self.pad_last:
                batches.append((tail, len(tail)))
            else:
                stats["dropped_items"] += sum(
                    1 for r in tail for e in r if _entry_is_example(e)
                )
                logger.info(
                    "Packed epoch dropped %d tail items in %d partial-batch "
                    "rows (drop_last parity; they re-enter next epoch's "
                    "shuffle).",
                    stats["dropped_items"], len(tail),
                )

        def local_slice(batch_rows):
            # pad the global tail by repeating the last REAL row (eval
            # pad_last contract), then take this host's contiguous slice
            padded = batch_rows + [batch_rows[-1]] * (rpb - len(batch_rows))
            return padded[lo:lo + local_rows]

        def submit(pool, batch_rows):
            # one read per UNIQUE index: fragments of one chunk landing in
            # this host's slice share a single oracle read (the item is
            # consumed read-only by the collate slicing), instead of
            # re-assembling and re-tokenizing the chunk once per fragment
            futures_by_index: dict = {}

            def read(entry):
                idx = entry_index(entry)
                if idx not in futures_by_index:
                    futures_by_index[idx] = pool.submit(
                        oracle_read, self.dataset, idx,
                        retries=self.read_retries, epoch=ek,
                    )
                return futures_by_index[idx]

            return [
                [read(entry) for entry in row]
                for row in local_slice(batch_rows)
            ]

        def materialize(entry, item):
            """Plan entry + its oracle-read item -> collate entry (the raw
            item, or the fragment re-pointed at it)."""
            if isinstance(entry, ChunkFragment):
                return dataclasses.replace(entry, item=item)
            return item

        def emit_global(batch_rows, real_rows, row_items):
            splitting = self.splitting != "off"
            collated = collate_packed(
                row_items, self.tokenizer, max_seq_len=self.max_seq_len,
                max_segments=self.max_segments, with_provenance=splitting,
            )
            inputs, labels = collated[0], collated[1]
            provenance = collated[2] if splitting else None
            # zero the mask of LOCAL rows that are global pad rows
            for r in range(local_rows):
                if lo + r >= real_rows:
                    labels["segment_mask"][r] = 0
            real_entries = [
                e for row in batch_rows[:real_rows] for e in row
            ]
            n_examples = sum(
                1 for e in real_entries if _entry_is_example(e)
            )
            stats["real_tokens"] += sum(
                entry_tokens(e) for e in real_entries
            )
            stats["supervised_tokens"] += sum(
                entry_tokens(e) for e in real_entries
                if _entry_is_example(e)
            )
            stats["physical_tokens"] += rpb * self.max_seq_len
            stats["padmax_tokens"] += n_examples * self.max_seq_len
            stats["rows"] += real_rows
            stats["batches"] += 1
            stats["items"] += n_examples
            self._count_fragments(
                batch_rows[:real_rows], stats, tokens_of=entry_tokens
            )
            # GLOBAL example count: what row-weighted metrics key on
            segments = n_examples
            return PackedBatch(
                inputs=inputs, labels=labels, rows=rpb, segments=segments,
                seq=self.max_seq_len, provenance=provenance,
            )

        # ONE pool for the epoch, reads submitted a batch ahead: the next
        # batch's item reads overlap this batch's collate and the device
        # step, mirroring the single-process path's sliding read window
        with ThreadPoolExecutor(max_workers=self.n_jobs) as pool:
            pending: deque = deque()
            for i in range(min(2, len(batches))):
                pending.append(submit(pool, batches[i][0]))
            for i, (batch_rows, real_rows) in enumerate(batches):
                futures = pending.popleft()
                if i + 2 < len(batches):
                    pending.append(submit(pool, batches[i + 2][0]))
                row_items = [
                    [
                        materialize(entry, f.result())
                        for entry, f in zip(plan_row, frow)
                    ]
                    for plan_row, frow in zip(
                        local_slice(batch_rows), futures
                    )
                ]
                yield emit_global(batch_rows, real_rows, row_items)

    def __iter__(self):
        if self.process_count > 1:
            yield from self._iter_oracle()
            return
        indices = [int(i) for i in self.sampler.epoch_indices(self._epoch)]
        self._last_stats = stats = self._new_stats()
        packer = self._make_packer()
        pending_rows: List[list] = []

        def drain():
            while len(pending_rows) >= self.rows_per_batch:
                batch_rows = pending_rows[: self.rows_per_batch]
                del pending_rows[: self.rows_per_batch]
                yield self._emit(batch_rows, stats)

        if indices:
            with ThreadPoolExecutor(max_workers=self.n_jobs) as pool:

                def read(i):
                    return _read_with_retry(
                        self.dataset, i, retries=self.read_retries
                    )

                futures: deque = deque()
                it = iter(indices)
                for idx in indices[: self.read_window]:
                    futures.append(pool.submit(read, idx))
                    next(it)
                while futures:
                    # results consumed in SUBMISSION order — the epoch
                    # ordering is what row assignment must follow
                    item = futures.popleft().result()
                    nxt = next(it, None)
                    if nxt is not None:
                        futures.append(pool.submit(read, nxt))
                    pending_rows.extend(
                        packer.add(
                            item, len(item.input_ids), _item_span(item)
                        )
                    )
                    yield from drain()
        pending_rows.extend(packer.flush())
        yield from drain()
        if pending_rows:
            if self.pad_last:
                real = len(pending_rows)
                pad = self.rows_per_batch - real
                yield self._emit(
                    pending_rows + [pending_rows[-1]] * pad, stats,
                    real_rows=real,
                )
            else:
                # drop-accounting follows the label carriers: an example
                # whose keep_labels fragment sits in a dropped tail row is
                # dropped (whatever sibling context landed earlier carries
                # segment_mask 0 anyway), so items + dropped == visited
                stats["dropped_items"] += sum(
                    1 for r in pending_rows for e in r if _entry_is_example(e)
                )
                logger.info(
                    "Packed epoch dropped %d tail items in %d partial-batch "
                    "rows (drop_last parity; they re-enter next epoch's "
                    "shuffle).",
                    stats["dropped_items"], len(pending_rows),
                )

    @property
    def epoch_stats(self) -> Optional[dict]:
        """Token accounting of the last (or in-progress) epoch:
        ``padding_waste_pct`` = the PAD fraction of physical tokens (the
        FLOP-waste number the splitting packer drives down);
        ``packing_efficiency`` = SUPERVISED tokens / physical tokens — it
        deliberately excludes sibling fragments' ignore-indexed tokens
        (label-less, gradient-less under block-diagonal attention), so a
        run that fills every hole with unsupervised fragments cannot
        report a dishonest 1.0; without splitting the two numbers are
        complements as before. ``padmax_waste_pct`` is what the pad-to-max
        path would have wasted on the same items."""
        s = self._last_stats
        if not s:
            return None
        out = dict(s)
        if s["physical_tokens"]:
            out["packing_efficiency"] = round(
                s["supervised_tokens"] / s["physical_tokens"], 4
            )
            out["padding_waste_pct"] = round(
                100.0 * (1.0 - s["real_tokens"] / s["physical_tokens"]), 2
            )
        if s["padmax_tokens"]:
            out["padmax_waste_pct"] = round(
                100.0 * (1.0 - s["real_tokens"] / s["padmax_tokens"]), 2
            )
        return out
