"""Sequence packing for the training/eval input path.

PR 4's length-bucketed batching pads each item only to its bucket, cutting
padding waste 45.7% -> 12.1% on the synthetic NQ mix — but every remaining
pad row still burns attention/FFN FLOPs, and each occupied bucket costs its
own compiled program. This module removes the residual waste the way
large-scale pretraining stacks do (RoBERTa FULL-SENTENCES packing; T5/PaLM
example packing with segment masks): CONCATENATE short chunks into one
fixed ``max_seq_len`` row, so ~every token is a real token and the train
step compiles exactly ONE ``(rows, max_seq_len)`` program.

The pieces:

- :class:`SequencePacker` — greedy first-fit binning of tokenized chunks
  into rows, walking the SAME deterministic weighted/shuffled epoch order
  the samplers draw (packing changes row composition, never item order);
- :func:`collate_packed` — one packed batch: ``input_ids`` /
  ``attention_mask`` / ``token_type_ids`` planes plus ``segment_ids``
  (1..S per segment, 0 on pad — also the attention kernels' block-diagonal
  mask operand), per-segment ``position_ids`` (reset to 0 at every segment
  boundary), ``segment_starts`` (each segment's [CLS] row index, for the
  per-segment pooled heads) and per-SEGMENT labels ``[rows, S]`` with a
  ``segment_mask`` validity plane — the scatter map back to original chunk
  indices is simply row-major segment order over ``segment_mask``;
- :class:`PackedDataLoader` — the train/eval loader, mirroring
  ``BucketedDataLoader``'s reader pipeline, epoch-order preservation,
  drop-last/pad-last discipline and token accounting
  (``epoch_stats['packing_efficiency']`` = real tokens / physical tokens).

Attention correctness is the ops layer's job: ``segment_ids`` rides the
kernels' mask operand and every regime (fused / q-blocked / streaming,
forward AND backward) applies the block-diagonal permission grid
``q_seg == k_seg != 0`` (ops/flash_attention.py, ops/flash_streaming.py).

Multi-host note: packing (like bucketing) is content-dependent — row
composition depends on chunk lengths, which every host must agree on for
step shapes to stay in lockstep. The multi-host path solves this with the
SHARED LENGTH ORACLE (:func:`oracle_read` / :func:`oracle_epoch_lengths`):
item reads pin the dataset's chunk-sampling RNG to a pure function of
``(ORACLE_SEED, index)``, so every host materializes bit-identical items
and derives the SAME per-epoch pack/bucket plan from the deterministic
epoch ordering — each host then collates only its contiguous row slice of
every planned global batch. No coordination traffic; the plan is pure
function of (seed, lengths).
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from .loader import _read_with_retry

logger = logging.getLogger(__name__)

# Seed of the shared length oracle: item reads under the oracle pin the
# dataset's chunk-sampling RNG to default_rng([ORACLE_SEED, index]), making
# every read a pure function of the index — the property that lets N hosts
# agree on every item length (and therefore on the whole epoch's bucket/
# pack plan) without exchanging a byte.
ORACLE_SEED = 0x0AC1E

# dataset.rng swap is process-global state; serialize oracle reads of
# rng-carrying datasets (rng-less datasets — the common deterministic
# corpora — read fully parallel)
_ORACLE_LOCK = threading.Lock()


def oracle_read(dataset, index: int, *, retries: int = 3, epoch: int = 0):
    """One deterministic item read: when the dataset carries a chunk-
    sampling ``rng``, it is swapped for a throwaway seeded by
    ``(ORACLE_SEED, epoch, index)`` for the duration — the read becomes a
    pure function of ``(epoch, index)``, identical on every host and on
    every repeat (the length pass and the later collate pass see the SAME
    item), while still drawing FRESH chunks each epoch exactly like the
    single-host live-rng path does. The training draw stream is
    untouched."""
    if getattr(dataset, "rng", None) is None:
        return _read_with_retry(dataset, int(index), retries=retries)
    with _ORACLE_LOCK:
        saved = dataset.rng
        dataset.rng = np.random.default_rng(
            np.random.SeedSequence([ORACLE_SEED, int(epoch), int(index)])
        )
        try:
            return _read_with_retry(dataset, int(index), retries=retries)
        finally:
            dataset.rng = saved


def _oracle_epoch_key(dataset, epoch: int) -> int:
    """Cache-key epoch component: deterministic (rng-less) datasets return
    the same item every epoch, so their lengths are cached ONCE across the
    whole run; stochastic-chunk datasets draw per-epoch under the oracle
    and their lengths are cached per epoch."""
    return int(epoch) if getattr(dataset, "rng", None) is not None else 0


def oracle_epoch_lengths(dataset, indices, *, cache: Dict[tuple, int],
                         n_jobs: int, read_retries: int,
                         epoch: int = 0) -> List[int]:
    """Item lengths for ``indices`` under the shared oracle, reading each
    UNIQUE ``(epoch, index)`` at most once (``cache`` persists across
    epochs and is EXACT here — oracle reads are reproducible, unlike the
    planning-only estimates of :func:`epoch_item_lengths`).

    Cost model: deterministic (rng-less) corpora read fully parallel and
    their lengths are cached ONCE for the whole run; stochastic-chunk
    (rng-carrying) datasets re-draw per epoch AND serialize on the oracle
    lock (``dataset.rng`` is shared mutable state — there is no parallel
    read under a pinned generator), so every host pays one serial
    materialization pass over the epoch per epoch. That is the price of
    host-agreed plans with live chunk re-sampling; corpora where it bites
    should pre-tokenize (drop the rng) or accept frozen epoch-0 draws."""
    ek = _oracle_epoch_key(dataset, epoch)
    missing = sorted({int(i) for i in indices if (ek, int(i)) not in cache})
    if missing:
        with ThreadPoolExecutor(max_workers=max(1, n_jobs)) as pool:
            for idx, item in zip(
                missing,
                pool.map(
                    lambda i: oracle_read(
                        dataset, i, retries=read_retries, epoch=ek
                    ),
                    missing,
                ),
            ):
                cache[(ek, idx)] = len(item.input_ids)
    return [cache[(ek, int(i))] for i in indices]

# Per-row segment cap: keeps the per-segment label planes ([rows, S]) and
# the model's per-segment head outputs at one static shape. 8 comfortably
# covers the NQ chunk mix at max_seq_len 384-512 (min chunk ~ question+CLS/
# SEP overhead ~ 35 tokens only for degenerate documents).
DEFAULT_MAX_SEGMENTS = 8

# Bounded open-row window of the greedy first-fit packer: more open rows =
# tighter packing (more chances to fill a gap) at the cost of a longer
# emission delay. Measured on the synthetic NQ mix (seq 512, drop-last
# accounting): window 8 -> 3.16% waste, 16 -> 2.70%, 32 -> 2.40%, 64 ->
# 2.44% — saturation at 32. (The residual is the MIX's floor, not the
# packer's: its 463-token chunks leave a 49-token hole no chunk can fill,
# ~1.6% for any non-splitting packer; on continuous NQ-like length mixes
# the same packer lands under 2%, pinned in tests/test_packing.py.)
DEFAULT_OPEN_ROWS = 32


def parse_sequence_packing(spec) -> bool:
    """Flag domain of ``--sequence_packing``: truthy strings/bools -> on,
    ``off``/``none``/``0``/``false`` (or None/False) -> off."""
    if spec is None or spec is False:
        return False
    if spec is True:
        return True
    s = str(spec).strip().lower()
    return s not in ("off", "none", "0", "false", "")


# LR-schedule planning reads item LENGTHS, which means materializing items
# (chunk assembly + tokenization). Bound that pre-training pass: past this
# many items the planners simulate on the epoch ordering's prefix and scale
# the step count — the length POPULATION is what drives packing/bucketing
# density, and a 4k prefix of a shuffled ordering samples it tightly.
PLAN_SAMPLE_ITEMS = 4096


def epoch_item_lengths(dataset, sampler, epoch, *, cache: Dict[int, int],
                       n_jobs: int, read_retries: int,
                       max_items: Optional[int] = None,
                       oracle: bool = False) -> List[int]:
    """Item lengths in one epoch's order (truncated to ``max_items`` when
    given), reading each UNIQUE index at most once (``cache`` persists
    across epochs — for stochastic-chunk datasets the cached length is one
    draw, an estimate by construction). The dataset's chunk-sampling RNG,
    when it has one, is swapped for a throwaway during the reads so
    PLANNING never perturbs the training draw stream. Shared by the packed
    and bucketed loaders' LR-schedule step planning. ``oracle=True``
    switches the reads to the shared length oracle (per-index pinned RNG):
    exact and host-invariant — what multi-host planning must use, since a
    host-divergent step estimate would diverge the LR schedule itself."""
    indices = [int(i) for i in sampler.epoch_indices(epoch)]
    if max_items is not None:
        indices = indices[:max_items]
    if oracle:
        return oracle_epoch_lengths(
            dataset, indices, cache=cache, n_jobs=n_jobs,
            read_retries=read_retries, epoch=epoch,
        )
    missing = sorted({i for i in indices if i not in cache})
    if missing:
        saved_rng = getattr(dataset, "rng", None)
        if saved_rng is not None:
            dataset.rng = np.random.default_rng(0)
        try:
            with ThreadPoolExecutor(max_workers=max(1, n_jobs)) as pool:
                for idx, item in zip(
                    missing,
                    pool.map(
                        lambda i: _read_with_retry(
                            dataset, i, retries=read_retries
                        ),
                        missing,
                    ),
                ):
                    cache[idx] = len(item.input_ids)
        finally:
            if saved_rng is not None:
                dataset.rng = saved_rng
    return [cache[i] for i in indices]


def plan_scaled_count(dataset, sampler, epoch, *, cache: Dict[int, int],
                      n_jobs: int, read_retries: int, simulate,
                      oracle: bool = False) -> int:
    """Shared LR-schedule planning skeleton of the packed and bucketed
    loaders: read the epoch's item lengths (prefix-bounded by
    ``PLAN_SAMPLE_ITEMS``), run the loader-specific ``simulate(lengths) ->
    count``, and scale the count back to the full epoch when only a prefix
    was read. Loader-specific tail handling (pad_last flushes, rows-per-
    batch division) stays with the caller — it must NOT be prefix-scaled."""
    n_total = len(sampler.epoch_indices(epoch))
    lengths = epoch_item_lengths(
        dataset, sampler, epoch, cache=cache, n_jobs=n_jobs,
        read_retries=read_retries, max_items=PLAN_SAMPLE_ITEMS,
        oracle=oracle,
    )
    count = simulate(lengths)
    if lengths and n_total > len(lengths):
        count = int(round(count * n_total / len(lengths)))
    return count


class SequencePacker:
    """Greedy first-fit packer: items arrive in epoch order, each is placed
    into the FIRST open row with room (and a free segment slot); when none
    fits and the open-row window is full, the FULLEST open row is emitted
    (ties to the oldest) — finalizing the best-packed row keeps the
    emptier ones around to catch fillers, measured 5.2% -> 3.5% waste at
    window 8 on the synthetic NQ mix vs emitting the oldest. Rows that
    fill exactly (or hit ``max_segments``) close eagerly. Pure function of
    the item sequence — deterministic under the deterministic epoch
    orderings the samplers draw."""

    def __init__(self, max_seq_len: int, *,
                 max_segments: int = DEFAULT_MAX_SEGMENTS,
                 open_rows: int = DEFAULT_OPEN_ROWS):
        self.max_seq_len = int(max_seq_len)
        self.max_segments = max(1, int(max_segments))
        self.open_rows = max(1, int(open_rows))
        self._open: List[tuple] = []  # (items, used_tokens)

    def add(self, item, length: int) -> List[list]:
        """Place one item; returns the (possibly empty) list of COMPLETED
        rows this placement closed, each a list of items in row order."""
        length = int(length)
        if length > self.max_seq_len:
            raise ValueError(
                f"item of length {length} exceeds max_seq_len "
                f"{self.max_seq_len} (the collate would reject it too)"
            )
        done: List[list] = []
        for i, (items, used) in enumerate(self._open):
            if used + length <= self.max_seq_len and len(items) < self.max_segments:
                items.append(item)
                used += length
                if used == self.max_seq_len or len(items) == self.max_segments:
                    done.append(items)
                    del self._open[i]
                else:
                    self._open[i] = (items, used)
                return done
        if len(self._open) >= self.open_rows:
            fullest = max(
                range(len(self._open)), key=lambda i: self._open[i][1]
            )
            done.append(self._open.pop(fullest)[0])
        self._open.append(([item], length))
        return done

    def flush(self) -> List[list]:
        """Emit every open row (epoch end), oldest first."""
        done = [items for items, _ in self._open]
        self._open = []
        return done


class PackedBatch(NamedTuple):
    """One collated packed batch: ``rows`` rows of ``seq`` tokens holding
    ``segments`` real segments (= original examples); pad rows (eval tail
    padding) repeat the last real row with ``segment_mask`` zeroed, so
    masked losses/metrics skip them without trimming."""

    inputs: dict
    labels: dict
    rows: int
    segments: int
    seq: int


def collate_packed(row_items: Sequence[list], tokenizer, *,
                   max_seq_len: int, max_segments: int = DEFAULT_MAX_SEGMENTS,
                   with_labels: bool = True):
    """Collate packed rows (lists of DatasetItem/ChunkItem) into the packed
    batch schema.

    Inputs (all ``[rows, L]`` int32 except ``segment_starts``):
      - ``input_ids``: concatenated chunk ids, pad_token_id elsewhere;
      - ``attention_mask``: 1 on real tokens (= ``segment_ids > 0``);
      - ``token_type_ids``: the plain collate's BERT rule applied WITHIN
        each segment (0 through its first [SEP], 1 after);
      - ``segment_ids``: 1..S per segment, 0 on pad — the attention
        kernels' block-diagonal mask operand;
      - ``position_ids``: 0..len(seg)-1 within each segment (position
        embeddings reset at every boundary), 0 on pad;
      - ``segment_starts`` ``[rows, S]``: each segment's [CLS] row index
        (0 for absent segments — gathered rows are masked downstream).

    Labels (``[rows, S]``; ``with_labels=False`` skips them for pure
    inference): ``start_class``/``end_class`` are ROW-ABSOLUTE token
    indices (chunk-relative index + segment offset; -1 for spanless chunks
    AND absent segments — the span CE's ignore_index), ``start_reg``/
    ``end_reg``/``cls`` as in the plain collate, plus ``segment_mask``
    (1 = real segment) which the packed loss keys every mean on.
    """
    R, L, S = len(row_items), int(max_seq_len), int(max_segments)
    pad_id = tokenizer.pad_token_id
    sep_id = tokenizer.sep_token_id
    is_bert = getattr(tokenizer, "model_name", "bert") == "bert"

    input_ids = np.full((R, L), pad_id, dtype=np.int32)
    token_type_ids = np.zeros((R, L), dtype=np.int32)
    segment_ids = np.zeros((R, L), dtype=np.int32)
    position_ids = np.zeros((R, L), dtype=np.int32)
    segment_starts = np.zeros((R, S), dtype=np.int32)
    segment_mask = np.zeros((R, S), dtype=np.int32)

    start_class = np.full((R, S), -1, dtype=np.int32)
    end_class = np.full((R, S), -1, dtype=np.int32)
    start_reg = np.zeros((R, S), dtype=np.float32)
    end_reg = np.zeros((R, S), dtype=np.float32)
    cls = np.zeros((R, S), dtype=np.int32)

    for r, items in enumerate(row_items):
        assert len(items) <= S, (len(items), S)
        off = 0
        for s, item in enumerate(items):
            row = item.input_ids
            n = len(row)
            assert off + n <= L, (
                f"packed row overflows max_seq_len {L} at segment {s} "
                f"(offset {off} + {n})"
            )
            input_ids[r, off:off + n] = row
            segment_ids[r, off:off + n] = s + 1
            position_ids[r, off:off + n] = np.arange(n, dtype=np.int32)
            if is_bert:
                # segment 0 up to and including the first [SEP] WITHIN this
                # packed segment, 1 after (collate.py:42-51 semantics)
                sep_pos = row.index(sep_id) if sep_id in row else n - 1
                token_type_ids[r, off + sep_pos + 1:off + n] = 1
            segment_starts[r, s] = off
            segment_mask[r, s] = 1
            if with_labels:
                if item.start_id >= 0:
                    start_class[r, s] = item.start_id + off
                    end_class[r, s] = item.end_id + off
                start_reg[r, s] = item.start_position
                end_reg[r, s] = item.end_position
                cls[r, s] = item.label_id
            off += n

    inputs = {
        "input_ids": input_ids,
        "attention_mask": (segment_ids > 0).astype(np.int32),
        "token_type_ids": token_type_ids,
        "segment_ids": segment_ids,
        "position_ids": position_ids,
        "segment_starts": segment_starts,
    }
    if not with_labels:
        return inputs, segment_mask
    labels = {
        "start_class": start_class,
        "end_class": end_class,
        "start_reg": start_reg,
        "end_reg": end_reg,
        "cls": cls,
        "segment_mask": segment_mask,
    }
    return inputs, labels


class PackedDataLoader:
    """Prefetching loader producing packed ``(rows, max_seq_len)`` batches.

    Walks ``sampler.epoch_indices(epoch)`` (the exact ordering the plain
    and bucketed loaders batch — weighted sampling preserved), reads items
    through the same retrying thread pool, bins them with the greedy
    first-fit :class:`SequencePacker`, and emits a :class:`PackedBatch`
    every ``rows_per_batch`` completed rows. Train mode (``pad_last=False``)
    drops the partial final BATCH of rows at epoch end (drop_last parity);
    eval mode (``pad_last=True``) pads it by repeating the last real row
    with ``segment_mask`` zeroed, so consumers need no trimming.

    Multi-host (``sampler.process_count > 1``): every host derives the SAME
    epoch pack plan from the shared length oracle (item lengths are a pure
    function of the index under :func:`oracle_read`) and collates only its
    contiguous ``rows_per_batch / process_count`` row slice of each planned
    global batch — step shapes stay in lockstep with zero coordination
    traffic. ``rows``/``segments`` on the emitted batches stay GLOBAL
    counts (what metric weighting and partial-batch trimming key on).
    """

    def __init__(
        self,
        dataset,
        sampler,
        tokenizer,
        *,
        max_seq_len: int,
        rows_per_batch: int,
        max_segments: int = DEFAULT_MAX_SEGMENTS,
        open_rows: int = DEFAULT_OPEN_ROWS,
        n_jobs: int = 4,
        read_window: Optional[int] = None,
        read_retries: int = 3,
        pad_last: bool = False,
    ):
        self.process_index = int(getattr(sampler, "process_index", 0))
        self.process_count = int(getattr(sampler, "process_count", 1))
        if self.process_count > 1 and rows_per_batch % self.process_count:
            raise ValueError(
                f"rows_per_batch {rows_per_batch} must divide over "
                f"{self.process_count} hosts (each host collates its "
                f"contiguous row slice of every planned global batch)"
            )
        self.dataset = dataset
        self.sampler = sampler
        self.tokenizer = tokenizer
        self.max_seq_len = int(max_seq_len)
        self.rows_per_batch = max(1, int(rows_per_batch))
        self.max_segments = max(1, int(max_segments))
        self.open_rows = max(1, int(open_rows))
        self.n_jobs = max(1, n_jobs)
        self.read_window = (
            int(read_window) if read_window is not None else self.n_jobs * 8
        )
        self.read_retries = max(0, read_retries)
        self.pad_last = pad_last
        self._epoch = 0
        self._last_stats: Optional[dict] = None
        self._len_cache: Dict[int, int] = {}

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __len__(self) -> int:
        """UPPER-BOUND step estimate (each packed row holds >= 1 item, so an
        epoch takes at most ``len(sampler)`` steps). The LR schedule uses
        the much tighter :meth:`planned_epoch_steps` instead."""
        return len(self.sampler)

    # -- planning ---------------------------------------------------------

    def planned_epoch_steps(self, epoch: int) -> int:
        """Planned batch count of one epoch: simulate the packer over the
        epoch's item lengths (one length read per unique index, cached; on
        corpora past ``PLAN_SAMPLE_ITEMS`` the simulation runs on the epoch
        ordering's prefix and the row count is scaled — a whole extra
        tokenize pass before step 1 would dwarf what the plan buys). This
        is what the LR schedule should size against — ``len(self)`` is the
        pad-to-max upper bound and overshoots by ~the packing factor."""

        def simulate(lengths):
            packer = SequencePacker(
                self.max_seq_len, max_segments=self.max_segments,
                open_rows=self.open_rows,
            )
            rows = 0
            for length in lengths:
                rows += len(packer.add(None, length))
            return rows + len(packer.flush())

        rows = plan_scaled_count(
            self.dataset, self.sampler, epoch, cache=self._len_cache,
            n_jobs=self.n_jobs, read_retries=self.read_retries,
            simulate=simulate, oracle=self.process_count > 1,
        )
        if self.pad_last:
            return -(-rows // self.rows_per_batch)
        return rows // self.rows_per_batch

    # -- iteration --------------------------------------------------------

    def _emit(self, rows: List[list], stats: dict, *, real_rows=None):
        real = len(rows) if real_rows is None else int(real_rows)
        real_items = [it for row in rows[:real] for it in row]
        inputs, labels = collate_packed(
            rows, self.tokenizer, max_seq_len=self.max_seq_len,
            max_segments=self.max_segments,
        )
        if real < len(rows):
            # pad rows must not look like real examples
            labels["segment_mask"][real:] = 0
        segments = int(labels["segment_mask"].sum())
        stats["real_tokens"] += sum(len(it.input_ids) for it in real_items)
        stats["physical_tokens"] += len(rows) * self.max_seq_len
        stats["padmax_tokens"] += len(real_items) * self.max_seq_len
        stats["rows"] += real
        stats["batches"] += 1
        stats["items"] += len(real_items)
        return PackedBatch(
            inputs=inputs, labels=labels, rows=len(rows), segments=segments,
            seq=self.max_seq_len,
        )

    def _iter_oracle(self):
        """Multi-host epoch: plan globally from oracle lengths, collate the
        local row slice. Every host computes the identical plan (pure
        function of the deterministic epoch ordering + oracle lengths), so
        per-step shapes, segment counts and stats agree bit-for-bit across
        hosts while each host only materializes 1/process_count of the
        rows for the device."""
        indices = [int(i) for i in self.sampler.epoch_indices(self._epoch)]
        self._last_stats = stats = {
            "real_tokens": 0,
            "physical_tokens": 0,
            "padmax_tokens": 0,
            "rows": 0,
            "batches": 0,
            "items": 0,
            "dropped_items": 0,
        }
        lengths = oracle_epoch_lengths(
            self.dataset, indices, cache=self._len_cache,
            n_jobs=self.n_jobs, read_retries=self.read_retries,
            epoch=self._epoch,
        )
        packer = SequencePacker(
            self.max_seq_len, max_segments=self.max_segments,
            open_rows=self.open_rows,
        )
        rows: List[list] = []  # each row: list of (index, length)
        for idx, length in zip(indices, lengths):
            rows.extend(packer.add((idx, length), length))
        rows.extend(packer.flush())

        rpb = self.rows_per_batch
        local_rows = rpb // self.process_count
        lo = self.process_index * local_rows
        ek = _oracle_epoch_key(self.dataset, self._epoch)

        batches = [
            (rows[b * rpb:(b + 1) * rpb], rpb)
            for b in range(len(rows) // rpb)
        ]
        tail = rows[(len(rows) // rpb) * rpb:]
        if tail:
            if self.pad_last:
                batches.append((tail, len(tail)))
            else:
                stats["dropped_items"] += sum(len(r) for r in tail)
                logger.info(
                    "Packed epoch dropped %d tail items in %d partial-batch "
                    "rows (drop_last parity; they re-enter next epoch's "
                    "shuffle).",
                    stats["dropped_items"], len(tail),
                )

        def local_slice(batch_rows):
            # pad the global tail by repeating the last REAL row (eval
            # pad_last contract), then take this host's contiguous slice
            padded = batch_rows + [batch_rows[-1]] * (rpb - len(batch_rows))
            return padded[lo:lo + local_rows]

        def submit(pool, batch_rows):
            return [
                [
                    pool.submit(
                        oracle_read, self.dataset, idx,
                        retries=self.read_retries, epoch=ek,
                    )
                    for idx, _ in row
                ]
                for row in local_slice(batch_rows)
            ]

        def emit_global(batch_rows, real_rows, row_items):
            inputs, labels = collate_packed(
                row_items, self.tokenizer, max_seq_len=self.max_seq_len,
                max_segments=self.max_segments,
            )
            # zero the mask of LOCAL rows that are global pad rows
            for r in range(local_rows):
                if lo + r >= real_rows:
                    labels["segment_mask"][r] = 0
            real_items = [it for row in batch_rows[:real_rows] for it in row]
            stats["real_tokens"] += sum(length for _, length in real_items)
            stats["physical_tokens"] += rpb * self.max_seq_len
            stats["padmax_tokens"] += len(real_items) * self.max_seq_len
            stats["rows"] += real_rows
            stats["batches"] += 1
            stats["items"] += len(real_items)
            # GLOBAL segment count: what row-weighted metrics key on
            segments = sum(len(row) for row in batch_rows[:real_rows])
            return PackedBatch(
                inputs=inputs, labels=labels, rows=rpb, segments=segments,
                seq=self.max_seq_len,
            )

        # ONE pool for the epoch, reads submitted a batch ahead: the next
        # batch's item reads overlap this batch's collate and the device
        # step, mirroring the single-process path's sliding read window
        with ThreadPoolExecutor(max_workers=self.n_jobs) as pool:
            pending: deque = deque()
            for i in range(min(2, len(batches))):
                pending.append(submit(pool, batches[i][0]))
            for i, (batch_rows, real_rows) in enumerate(batches):
                futures = pending.popleft()
                if i + 2 < len(batches):
                    pending.append(submit(pool, batches[i + 2][0]))
                row_items = [[f.result() for f in row] for row in futures]
                yield emit_global(batch_rows, real_rows, row_items)

    def __iter__(self):
        if self.process_count > 1:
            yield from self._iter_oracle()
            return
        indices = [int(i) for i in self.sampler.epoch_indices(self._epoch)]
        self._last_stats = stats = {
            "real_tokens": 0,
            "physical_tokens": 0,
            "padmax_tokens": 0,
            "rows": 0,
            "batches": 0,
            "items": 0,
            "dropped_items": 0,
        }
        packer = SequencePacker(
            self.max_seq_len, max_segments=self.max_segments,
            open_rows=self.open_rows,
        )
        pending_rows: List[list] = []

        def drain():
            while len(pending_rows) >= self.rows_per_batch:
                batch_rows = pending_rows[: self.rows_per_batch]
                del pending_rows[: self.rows_per_batch]
                yield self._emit(batch_rows, stats)

        if indices:
            with ThreadPoolExecutor(max_workers=self.n_jobs) as pool:

                def read(i):
                    return _read_with_retry(
                        self.dataset, i, retries=self.read_retries
                    )

                futures: deque = deque()
                it = iter(indices)
                for idx in indices[: self.read_window]:
                    futures.append(pool.submit(read, idx))
                    next(it)
                while futures:
                    # results consumed in SUBMISSION order — the epoch
                    # ordering is what row assignment must follow
                    item = futures.popleft().result()
                    nxt = next(it, None)
                    if nxt is not None:
                        futures.append(pool.submit(read, nxt))
                    pending_rows.extend(packer.add(item, len(item.input_ids)))
                    yield from drain()
        pending_rows.extend(packer.flush())
        yield from drain()
        if pending_rows:
            if self.pad_last:
                real = len(pending_rows)
                pad = self.rows_per_batch - real
                yield self._emit(
                    pending_rows + [pending_rows[-1]] * pad, stats,
                    real_rows=real,
                )
            else:
                stats["dropped_items"] += sum(len(r) for r in pending_rows)
                logger.info(
                    "Packed epoch dropped %d tail items in %d partial-batch "
                    "rows (drop_last parity; they re-enter next epoch's "
                    "shuffle).",
                    stats["dropped_items"], len(pending_rows),
                )

    @property
    def epoch_stats(self) -> Optional[dict]:
        """Token accounting of the last (or in-progress) epoch:
        ``packing_efficiency`` = real tokens / physical tokens (the
        headline sequence-packing metric), ``padding_waste_pct`` its
        complement, ``padmax_waste_pct`` what the pad-to-max path would
        have wasted on the same items."""
        s = self._last_stats
        if not s:
            return None
        out = dict(s)
        if s["physical_tokens"]:
            eff = s["real_tokens"] / s["physical_tokens"]
            out["packing_efficiency"] = round(eff, 4)
            out["padding_waste_pct"] = round(100.0 * (1.0 - eff), 2)
        if s["padmax_tokens"]:
            out["padmax_waste_pct"] = round(
                100.0 * (1.0 - s["real_tokens"] / s["padmax_tokens"]), 2
            )
        return out
