"""Fixed-shape batch collation.

Parity target: reference split_dataset.py:480-520 (``collate_fun``) — pads the
batch, builds attention_mask and BERT token_type_ids, packs the 5-key label
dict, optional raw-items passthrough for inference.

TPU-first delta (SURVEY.md §7 hard part (a)): the reference pads to the
*per-batch max length* (split_dataset.py:484), giving dynamic shapes that
would retrigger XLA compilation every step. Here every batch is padded to the
static ``max_seq_len``, so one compiled program serves the whole run. The
attention mask is derived from true lengths (the reference's ``tokens > 0``
trick breaks for RoBERTa whose pad id is 1).

Outputs are numpy (host) arrays; device placement/sharding happens in the
training loop.
"""

from __future__ import annotations

import functools
from typing import List, Optional

import numpy as np


def collate_fun(items, tokenizer, *, max_seq_len: Optional[int] = None, return_items: bool = False):
    batch_size = len(items)
    pad_token_id = tokenizer.pad_token_id

    lengths = np.asarray([len(item.input_ids) for item in items], dtype=np.int32)
    target_len = int(max_seq_len) if max_seq_len is not None else int(lengths.max())
    assert lengths.max() <= target_len, (
        f"Item of length {lengths.max()} exceeds static max_seq_len {target_len}."
    )

    tokens = np.full((batch_size, target_len), pad_token_id, dtype=np.int32)
    token_type_ids = np.zeros((batch_size, target_len), dtype=np.int32)

    is_bert = getattr(tokenizer, "model_name", "bert") == "bert"
    sep_token_id = tokenizer.sep_token_id

    for i, item in enumerate(items):
        row = item.input_ids
        tokens[i, : len(row)] = row
        if is_bert:
            # segment 0 up to and including the first [SEP], segment 1 after
            # (split_dataset.py:487-495); padding stays segment 0 and is
            # masked out anyway.
            sep_pos = row.index(sep_token_id) if sep_token_id in row else len(row) - 1
            token_type_ids[i, sep_pos + 1 : len(row)] = 1

    positions = np.arange(target_len, dtype=np.int32)[None, :]
    attention_mask = (positions < lengths[:, None]).astype(np.int32)

    inputs = {
        "input_ids": tokens,
        "attention_mask": attention_mask,
        "token_type_ids": token_type_ids,
    }

    labels = {
        "start_class": np.asarray([item.start_id for item in items], dtype=np.int32),
        "end_class": np.asarray([item.end_id for item in items], dtype=np.int32),
        "start_reg": np.asarray([item.start_position for item in items], dtype=np.float32),
        "end_reg": np.asarray([item.end_position for item in items], dtype=np.float32),
        "cls": np.asarray([item.label_id for item in items], dtype=np.int32),
    }

    if return_items:
        return [inputs, labels, items]

    return [inputs, labels]


def make_collate_fun(tokenizer, *, max_seq_len: Optional[int] = None, return_items: bool = False):
    """Bind tokenizer/shape args (reference init.py:204-205 ``init_collate_fun``)."""
    return functools.partial(
        collate_fun, tokenizer=tokenizer, max_seq_len=max_seq_len, return_items=return_items
    )


def rebind_collate_seq(collate, max_seq_len: int):
    """A copy of a bound collate with its static pad length replaced —
    length-bucketed batching collates each bucket at the BUCKET seq instead
    of the global max (data/bucketing.py), everything else (tokenizer,
    return_items) unchanged."""
    if not isinstance(collate, functools.partial) or collate.func is not collate_fun:
        raise TypeError(
            f"rebind_collate_seq needs a make_collate_fun-style partial of "
            f"collate_fun, got {collate!r}"
        )
    kwargs = dict(collate.keywords)
    kwargs["max_seq_len"] = int(max_seq_len)
    return functools.partial(collate.func, *collate.args, **kwargs)
