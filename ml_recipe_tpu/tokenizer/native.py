"""ctypes bindings for the C++ WordPiece backend (native/qatok).

The shared library is built with ``make -C native`` (g++, no deps). When the
.so is absent this module reports unavailable and the pure-Python
implementation serves — behaviour is identical either way: the native path
only ever receives ASCII text, where its semantics are exactly the Python
spec's (see native/qatok/wordpiece.cc header).
"""

from __future__ import annotations

import ctypes
from typing import List, Optional

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    from ml_recipe_tpu.utils.nativelib import load_native_lib

    lib = load_native_lib("libqatok.so")
    if lib is None:
        return None
    lib.qatok_wordpiece_new.restype = ctypes.c_void_p
    lib.qatok_wordpiece_new.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p]
    lib.qatok_wordpiece_free.argtypes = [ctypes.c_void_p]
    lib.qatok_vocab_size.restype = ctypes.c_int32
    lib.qatok_vocab_size.argtypes = [ctypes.c_void_p]
    lib.qatok_token_to_id.restype = ctypes.c_int32
    lib.qatok_token_to_id.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.qatok_wordpiece_encode.restype = ctypes.c_int32
    lib.qatok_wordpiece_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
    ]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


class NativeWordPiece:
    """Handle on a loaded C++ WordPiece vocab. ASCII text only — callers
    route non-ASCII to the Python implementation."""

    def __init__(self, vocab_file: str, *, lowercase: bool = True,
                 handle_chinese_chars: bool = False, unk_token: str = "[UNK]"):
        # handle_chinese_chars only affects CJK codepoints, which are
        # non-ASCII and therefore always routed to the Python path — the flag
        # is accepted for facade symmetry and has no native effect.
        del handle_chinese_chars
        lib = _load()
        if lib is None:
            raise RuntimeError("native qatok library not built (make -C native)")
        self._lib = lib
        self._handle = lib.qatok_wordpiece_new(
            vocab_file.encode(), int(lowercase), unk_token.encode()
        )
        if not self._handle:
            raise RuntimeError(
                f"qatok could not load vocab {vocab_file!r} (missing file or "
                f"missing {unk_token!r} entry)"
            )
        # per-thread buffers: the loaders encode from a ThreadPoolExecutor and
        # ctypes releases the GIL during the C call — a shared buffer races
        import threading

        self._tls = threading.local()

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.qatok_wordpiece_free(handle)
            self._handle = None

    def __len__(self) -> int:
        return int(self._lib.qatok_vocab_size(self._handle))

    def token_to_id(self, token: str) -> Optional[int]:
        i = int(self._lib.qatok_token_to_id(self._handle, token.encode()))
        return None if i < 0 else i

    def encode(self, text: str) -> List[int]:
        if not hasattr(self._tls, "buf"):
            self._tls.cap = 8192
            self._tls.buf = (ctypes.c_int32 * self._tls.cap)()

        # NUL would terminate the C string; the pipeline drops it anyway
        # (wordpiece.py:87 cp == 0), so strip before crossing the boundary.
        raw = text.encode().replace(b"\x00", b"")
        n = self._lib.qatok_wordpiece_encode(
            self._handle, raw, self._tls.buf, self._tls.cap
        )
        if n < 0:  # grow and retry
            self._tls.cap = max(-n, self._tls.cap * 2)
            self._tls.buf = (ctypes.c_int32 * self._tls.cap)()
            n = self._lib.qatok_wordpiece_encode(
                self._handle, raw, self._tls.buf, self._tls.cap
            )
        return list(self._tls.buf[:n])
