"""ctypes bindings for the C++ WordPiece backend (native/qatok).

The shared library is built with ``make -C native`` (g++, no deps). When the
.so is absent this module reports unavailable and the pure-Python
implementation serves — behaviour is identical either way: the native path
only ever receives ASCII text, where its semantics are exactly the Python
spec's (see native/qatok/wordpiece.cc header).
"""

from __future__ import annotations

import ctypes
from typing import List, Optional

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    from ml_recipe_tpu.utils.nativelib import load_native_lib

    lib = load_native_lib("libqatok.so")
    if lib is None:
        return None
    lib.qatok_wordpiece_new.restype = ctypes.c_void_p
    lib.qatok_wordpiece_new.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p]
    lib.qatok_wordpiece_free.argtypes = [ctypes.c_void_p]
    lib.qatok_vocab_size.restype = ctypes.c_int32
    lib.qatok_vocab_size.argtypes = [ctypes.c_void_p]
    lib.qatok_token_to_id.restype = ctypes.c_int32
    lib.qatok_token_to_id.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.qatok_wordpiece_encode.restype = ctypes.c_int32
    lib.qatok_wordpiece_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
    ]
    lib.qatok_bpe_new.restype = ctypes.c_void_p
    lib.qatok_bpe_new.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.qatok_bpe_free.argtypes = [ctypes.c_void_p]
    lib.qatok_bpe_vocab_size.restype = ctypes.c_int32
    lib.qatok_bpe_vocab_size.argtypes = [ctypes.c_void_p]
    lib.qatok_bpe_token_to_id.restype = ctypes.c_int32
    lib.qatok_bpe_token_to_id.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.qatok_bpe_encode.restype = ctypes.c_int32
    lib.qatok_bpe_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
    ]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def _encode_ids(lib_fn, handle, tls, text: str) -> List[int]:
    """Shared ctypes encode protocol: per-thread buffer (the loaders encode
    from a ThreadPoolExecutor and ctypes releases the GIL during the C call —
    a shared buffer races), NUL stripped (cannot cross the C-string boundary;
    the facade routes NUL-bearing texts to the Python path), grow-and-retry
    when the buffer is too small."""
    if not hasattr(tls, "buf"):
        tls.cap = 8192
        tls.buf = (ctypes.c_int32 * tls.cap)()

    raw = text.encode().replace(b"\x00", b"")
    n = lib_fn(handle, raw, tls.buf, tls.cap)
    if n < 0:  # grow and retry
        tls.cap = max(-n, tls.cap * 2)
        tls.buf = (ctypes.c_int32 * tls.cap)()
        n = lib_fn(handle, raw, tls.buf, tls.cap)
    return list(tls.buf[:n])


class NativeWordPiece:
    """Handle on a loaded C++ WordPiece vocab. ASCII text only — callers
    route non-ASCII to the Python implementation."""

    def __init__(self, vocab_file: str, *, lowercase: bool = True,
                 handle_chinese_chars: bool = False, unk_token: str = "[UNK]"):
        # handle_chinese_chars only affects CJK codepoints, which are
        # non-ASCII and therefore always routed to the Python path — the flag
        # is accepted for facade symmetry and has no native effect.
        del handle_chinese_chars
        lib = _load()
        if lib is None:
            raise RuntimeError("native qatok library not built (make -C native)")
        self._lib = lib
        self._handle = lib.qatok_wordpiece_new(
            vocab_file.encode(), int(lowercase), unk_token.encode()
        )
        if not self._handle:
            raise RuntimeError(
                f"qatok could not load vocab {vocab_file!r} (missing file or "
                f"missing {unk_token!r} entry)"
            )
        import threading

        self._tls = threading.local()

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.qatok_wordpiece_free(handle)
            self._handle = None

    def __len__(self) -> int:
        return int(self._lib.qatok_vocab_size(self._handle))

    def token_to_id(self, token: str) -> Optional[int]:
        i = int(self._lib.qatok_token_to_id(self._handle, token.encode()))
        return None if i < 0 else i

    def encode(self, text: str) -> List[int]:
        # NUL-stripping here IS the spec: the Python pipeline drops it too
        # (wordpiece.py:87 cp == 0).
        return _encode_ids(
            self._lib.qatok_wordpiece_encode, self._handle, self._tls, text
        )


class NativeByteLevelBPE:
    """Handle on a loaded C++ byte-level BPE (vocab.json + merges.txt).
    ASCII text only, no BPE-dropout — callers route non-ASCII or stochastic
    encodes to the Python implementation."""

    def __init__(self, vocab_file: str, merges_file: str):
        lib = _load()
        if lib is None:
            raise RuntimeError("native qatok library not built (make -C native)")
        self._lib = lib
        self._handle = lib.qatok_bpe_new(vocab_file.encode(), merges_file.encode())
        if not self._handle:
            raise RuntimeError(
                f"qatok could not load BPE files {vocab_file!r} / {merges_file!r}"
            )
        import threading

        self._tls = threading.local()

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.qatok_bpe_free(handle)
            self._handle = None

    def __len__(self) -> int:
        return int(self._lib.qatok_bpe_vocab_size(self._handle))

    def token_to_id(self, token: str) -> Optional[int]:
        i = int(self._lib.qatok_bpe_token_to_id(self._handle, token.encode()))
        return None if i < 0 else i

    def encode(self, text: str) -> List[int]:
        # NUL diverges from the Python spec here (byte-level BPE encodes byte
        # 0 as a real token) — the facade routes NUL-bearing texts to the
        # Python path; the helper's strip is only a belt against direct calls.
        return _encode_ids(
            self._lib.qatok_bpe_encode, self._handle, self._tls, text
        )
