"""First-party WordPiece tokenizer (BERT style).

Replaces the Rust ``tokenizers.BertWordPieceTokenizer`` dependency the
reference wraps in ``modules/model/model/tokenizer.py:26-31``. Implements the
standard BERT pipeline: text cleaning, optional lowercase + accent stripping,
punctuation splitting, optional CJK isolation, then greedy longest-match
WordPiece with ``##`` continuations.

Pure-Python reference implementation; a C++ backend with identical behaviour
can be swapped in through :class:`ml_recipe_tpu.tokenizer.facade.Tokenizer`.
"""

from __future__ import annotations

import unicodedata
from typing import Dict, List, Optional


def load_vocab(vocab_file: str) -> Dict[str, int]:
    vocab: Dict[str, int] = {}
    with open(vocab_file, "r", encoding="utf-8") as fh:
        for i, line in enumerate(fh):
            token = line.rstrip("\n")
            if token:
                vocab[token] = i
    return vocab


def _is_control(ch: str) -> bool:
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_whitespace(ch: str) -> bool:
    if ch in (" ", "\t", "\n", "\r"):
        return True
    return unicodedata.category(ch) == "Zs"


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (
        (0x4E00 <= cp <= 0x9FFF)
        or (0x3400 <= cp <= 0x4DBF)
        or (0x20000 <= cp <= 0x2A6DF)
        or (0x2A700 <= cp <= 0x2B73F)
        or (0x2B740 <= cp <= 0x2B81F)
        or (0x2B820 <= cp <= 0x2CEAF)
        or (0xF900 <= cp <= 0xFAFF)
        or (0x2F800 <= cp <= 0x2FA1F)
    )


class WordPieceTokenizer:
    def __init__(
        self,
        vocab_file: str,
        *,
        lowercase: bool = True,
        handle_chinese_chars: bool = False,
        unk_token: str = "[UNK]",
        max_input_chars_per_word: int = 100,
    ):
        self.vocab = load_vocab(vocab_file)
        self.inv_vocab = {i: t for t, i in self.vocab.items()}
        self.lowercase = lowercase
        self.handle_chinese_chars = handle_chinese_chars
        self.unk_token = unk_token
        self.max_input_chars_per_word = max_input_chars_per_word

    def __len__(self) -> int:
        return len(self.vocab)

    # -- basic tokenization ---------------------------------------------------

    def _clean_text(self, text: str) -> str:
        out = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or _is_control(ch):
                continue
            out.append(" " if _is_whitespace(ch) else ch)
        return "".join(out)

    def _tokenize_cjk(self, text: str) -> str:
        out = []
        for ch in text:
            if _is_cjk(ord(ch)):
                out.append(f" {ch} ")
            else:
                out.append(ch)
        return "".join(out)

    def _split_punctuation(self, word: str) -> List[str]:
        pieces: List[str] = []
        current: List[str] = []
        for ch in word:
            if _is_punctuation(ch):
                if current:
                    pieces.append("".join(current))
                    current = []
                pieces.append(ch)
            else:
                current.append(ch)
        if current:
            pieces.append("".join(current))
        return pieces

    def basic_tokenize(self, text: str) -> List[str]:
        text = self._clean_text(text)
        if self.handle_chinese_chars:
            text = self._tokenize_cjk(text)
        words: List[str] = []
        for word in text.split():
            if self.lowercase:
                word = word.lower()
                word = "".join(
                    ch for ch in unicodedata.normalize("NFD", word)
                    if unicodedata.category(ch) != "Mn"
                )
            words.extend(self._split_punctuation(word))
        return words

    # -- wordpiece ------------------------------------------------------------

    def wordpiece(self, word: str) -> List[str]:
        if len(word) > self.max_input_chars_per_word:
            return [self.unk_token]

        tokens: List[str] = []
        start = 0
        n = len(word)
        while start < n:
            end = n
            cur: Optional[str] = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = piece
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            tokens.append(cur)
            start = end
        return tokens

    # -- public API -----------------------------------------------------------

    def tokenize(self, text: str) -> List[str]:
        out: List[str] = []
        for word in self.basic_tokenize(text):
            out.extend(self.wordpiece(word))
        return out

    def encode(self, text: str) -> List[int]:
        """Token ids WITHOUT special tokens (callers add [CLS]/[SEP])."""
        unk_id = self.vocab[self.unk_token]
        return [self.vocab.get(t, unk_id) for t in self.tokenize(text)]

    def decode(self, ids: List[int], *, skip_special_tokens: bool = True) -> str:
        specials = {"[PAD]", "[SEP]", "[CLS]", "[UNK]", "[MASK]"}
        tokens = []
        for i in ids:
            tok = self.inv_vocab.get(int(i), self.unk_token)
            if skip_special_tokens and tok in specials:
                continue
            tokens.append(tok)
        # Matches the Rust WordPiece decoder with ``cleanup=True`` (the
        # reference's decode path, tokenizer.py:61): each non-first token is
        # either a ``##`` continuation (prefix stripped, no space) or gets a
        # leading space, and the cleanup substitution chain runs PER PIECE —
        # not on the joined string, so e.g. a lone apostrophe piece " '" is
        # never collapsed. Fuzz-verified in tests/test_tokenizer_diff.py.
        pieces = []
        for idx, tok in enumerate(tokens):
            if idx != 0:
                tok = tok[2:] if tok.startswith("##") else " " + tok
            for dirty, clean in (
                (" .", "."), (" ?", "?"), (" !", "!"), (" ,", ","),
                (" ' ", "' "), (" n't", "n't"), (" 'm", "'m"), (" 's", "'s"),
                (" 've", "'ve"), (" 're", "'re"),
            ):
                tok = tok.replace(dirty, clean)
            pieces.append(tok)
        return "".join(pieces)

    def token_to_id(self, token: str) -> Optional[int]:
        return self.vocab.get(token)
