"""First-party byte-level BPE tokenizer (RoBERTa/GPT-2 style).

Replaces the Rust ``tokenizers.ByteLevelBPETokenizer`` the reference wraps in
``modules/model/model/tokenizer.py:42-49``, including the optional BPE-dropout
(Provilkov et al., 2019) the reference exposes via ``--bpe_dropout``.
"""

from __future__ import annotations

import heapq
import json
import re
from functools import lru_cache
from typing import Dict, List, Optional, Tuple

import numpy as np


@lru_cache()
def bytes_to_unicode() -> Dict[int, str]:
    """GPT-2's reversible byte->printable-unicode map."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


try:
    # the exact GPT-2 pre-split pattern needs \p{L}/\p{N} classes (letters
    # exclude '_'; numbers include Nl/No like 'Ⅻ'/'½'), which stdlib `re`
    # cannot express — `regex` ships with transformers, so it is always
    # present in practice; the `re` fallback is approximate on those classes
    import regex as _re_mod

    _GPT2_SPLIT = _re_mod.compile(
        r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"
    )
except ImportError:  # pragma: no cover
    _GPT2_SPLIT = re.compile(
        r"'s|'t|'re|'ve|'m|'ll|'d| ?[^\W\d_]+| ?\d+| ?(?:[^\s\w]|_)+|\s+(?!\S)|\s+"
    )


class ByteLevelBPETokenizer:
    def __init__(
        self,
        vocab_file: str,
        merges_file: str,
        *,
        dropout: Optional[float] = None,
        rng: Optional[np.random.Generator] = None,
    ):
        with open(vocab_file, "r", encoding="utf-8") as fh:
            self.vocab: Dict[str, int] = json.load(fh)
        self.inv_vocab = {i: t for t, i in self.vocab.items()}

        self.merge_ranks: Dict[Tuple[str, str], int] = {}
        with open(merges_file, "r", encoding="utf-8") as fh:
            for i, line in enumerate(fh):
                line = line.strip()
                if not line or line.startswith("#version"):
                    continue
                a, _, b = line.partition(" ")
                self.merge_ranks[(a, b)] = len(self.merge_ranks)

        self.byte_encoder = bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        self.dropout = dropout
        self.rng = rng if rng is not None else np.random.default_rng()
        self._cache: Dict[str, List[str]] = {}

    def __len__(self) -> int:
        return len(self.vocab)

    def _bpe(self, token: str) -> List[str]:
        use_dropout = self.dropout is not None and self.dropout > 0
        if use_dropout:
            return self._bpe_dropout(token)
        if token in self._cache:
            return self._cache[token]

        word = list(token)
        while len(word) > 1:
            pairs = {(word[i], word[i + 1]) for i in range(len(word) - 1)}
            ranked = [
                (self.merge_ranks[p], p) for p in pairs if p in self.merge_ranks
            ]
            if not ranked:
                break
            _, best = min(ranked)
            merged: List[str] = []
            i = 0
            while i < len(word):
                if i < len(word) - 1 and (word[i], word[i + 1]) == best:
                    merged.append(word[i] + word[i + 1])
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = merged

        self._cache[token] = word
        return word

    def _bpe_dropout(self, token: str) -> List[str]:
        """BPE-dropout (Provilkov et al.) with the Rust library's QUEUE
        semantics (word.rs ``merge_all``): candidates pop in (rank,
        position) order; each pop rolls dropout — a skipped candidate goes
        to a side buffer and is RE-QUEUED as soon as any merge is accepted,
        so merging only stops when a run of consecutive drops exhausts the
        queue. (A naive re-roll-every-sweep scheme over-fragments: measured
        ~165 tokens vs Rust's ~152 at p=0.1 on the same text; permanent
        single-roll drops over-fragment even more, ~195.)"""
        syms = list(token)
        n = len(syms)
        nxt = list(range(1, n)) + [-1]
        prev = [-1] + list(range(n - 1))
        alive = [True] * n
        heap: List[tuple] = []
        skipped: List[tuple] = []

        def push(i: int) -> None:
            j = nxt[i]
            if j == -1:
                return
            r = self.merge_ranks.get((syms[i], syms[j]))
            if r is not None:
                heapq.heappush(heap, (r, i, syms[i], syms[j]))

        for i in range(n - 1):
            push(i)

        while heap:
            top = heapq.heappop(heap)
            if self.rng.random() < self.dropout:
                skipped.append(top)  # dies only if the queue empties first
                continue
            for t in skipped:
                heapq.heappush(heap, t)
            skipped.clear()

            _, i, a, b = top
            if not alive[i]:
                continue
            j = nxt[i]
            if j == -1 or syms[i] != a or syms[j] != b:
                # stale: a neighbour merge changed the pair — requeue the
                # position's CURRENT pair (rust re-pushes the corrected
                # candidate) and move on
                push(i)
                continue
            syms[i] = a + b
            alive[j] = False
            nxt[i] = nxt[j]
            if nxt[j] != -1:
                prev[nxt[j]] = i
            if prev[i] != -1:
                push(prev[i])
            push(i)

        return [s for k, s in enumerate(syms) if alive[k]]

    def tokenize(self, text: str) -> List[str]:
        out: List[str] = []
        for piece in _GPT2_SPLIT.findall(text):
            mapped = "".join(self.byte_encoder[b] for b in piece.encode("utf-8"))
            out.extend(self._bpe(mapped))
        return out

    def encode(self, text: str) -> List[int]:
        """Token ids WITHOUT special tokens (callers add <s>/</s>)."""
        unk = self.vocab.get("<unk>", 0)
        return [self.vocab.get(t, unk) for t in self.tokenize(text)]

    def decode(self, ids: List[int], *, skip_special_tokens: bool = True) -> str:
        # ``skip_special_tokens`` is accepted but inert: a Rust
        # ByteLevelBPETokenizer loaded from vocab/merges FILES (the
        # reference's construction, tokenizer.py:42-49) registers no added
        # special tokens, so its decode renders '<s>'/'</s>'/'<pad>' as
        # literal text regardless of the flag — fuzz-verified in
        # tests/test_tokenizer_diff.py.
        del skip_special_tokens
        text = ""
        for i in ids:
            tok = self.inv_vocab.get(int(i), "<unk>")
            text += tok
        # No strip: the Rust ByteLevel decoder preserves surrounding
        # whitespace exactly (fuzz-verified in tests/test_tokenizer_diff.py).
        raw = bytearray(self.byte_decoder.get(ch, ord(" ")) for ch in text)
        return raw.decode("utf-8", errors="replace")

    def token_to_id(self, token: str) -> Optional[int]:
        return self.vocab.get(token)
