from .facade import Tokenizer
from .wordpiece import WordPieceTokenizer
from .bpe import ByteLevelBPETokenizer
from .vocab_utils import write_synthetic_bert_vocab

__all__ = ["Tokenizer", "WordPieceTokenizer", "ByteLevelBPETokenizer", "write_synthetic_bert_vocab"]
