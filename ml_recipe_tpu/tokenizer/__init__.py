from .facade import Tokenizer
from .wordpiece import WordPieceTokenizer
from .bpe import ByteLevelBPETokenizer

__all__ = ["Tokenizer", "WordPieceTokenizer", "ByteLevelBPETokenizer"]
