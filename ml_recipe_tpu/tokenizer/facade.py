"""Uniform tokenizer facade.

Parity target: reference ``modules/model/model/tokenizer.py:8-93`` — one class
selecting WordPiece (BERT special tokens ``[PAD]/[SEP]/[CLS]/[UNK]``) or
byte-level BPE (RoBERTa ``<pad>/</s>/<s>/<unk>``) with a uniform
``encode``/``decode``/token-id-property API and optional BPE dropout.

Backend selection: the C++ implementation (``native/qatok``) is used when its
shared library has been built (~10x faster WordPiece, identical output);
otherwise the pure-Python implementations in this package serve as both the
behavioural spec and the fallback.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from .bpe import ByteLevelBPETokenizer
from .wordpiece import WordPieceTokenizer

logger = logging.getLogger(__name__)


def _try_native_backend():
    try:
        from . import native  # noqa: WPS433

        return native if native.available() else None
    except Exception:
        return None


class Tokenizer:
    def __init__(
        self,
        model_name: str,
        vocab_file: str,
        *,
        merges_file: Optional[str] = None,
        lowercase: bool = True,
        handle_chinese_chars: bool = False,
        dropout: Optional[float] = None,
        use_native: bool = True,
    ):
        self.model_name = model_name
        self._native = None

        if model_name == "bert":
            self._pad_token = "[PAD]"
            self._sep_token = "[SEP]"
            self._cls_token = "[CLS]"
            self._unk_token = "[UNK]"

            if dropout is not None:
                logger.warning("BPE dropout is not supported by the WordPiece tokenizer.")

            self.tokenizer = WordPieceTokenizer(
                vocab_file,
                lowercase=lowercase,
                handle_chinese_chars=handle_chinese_chars,
                unk_token=self._unk_token,
            )
            if use_native:
                backend = _try_native_backend()
                if backend is not None:
                    self._native = backend.NativeWordPiece(
                        vocab_file,
                        lowercase=lowercase,
                        handle_chinese_chars=handle_chinese_chars,
                        unk_token=self._unk_token,
                    )
                    logger.info("Using native C++ WordPiece backend.")
        elif model_name == "roberta":
            if merges_file is None:
                raise AttributeError("To use the byte-level BPE tokenizer, specify a merges file.")

            self._pad_token = "<pad>"
            self._sep_token = "</s>"
            self._cls_token = "<s>"
            self._unk_token = "<unk>"

            self.tokenizer = ByteLevelBPETokenizer(
                vocab_file=vocab_file, merges_file=merges_file, dropout=dropout
            )
            # native fast path: deterministic encodes only — BPE-dropout is
            # stochastic regularization and stays on the Python path
            if use_native and not dropout:
                backend = _try_native_backend()
                if backend is not None:
                    self._native = backend.NativeByteLevelBPE(vocab_file, merges_file)
                    logger.info("Using native C++ byte-level BPE backend.")
        else:
            raise NotImplementedError(
                f"Tokenizer initialization for model {model_name} is not implemented."
            )

    def __len__(self) -> int:
        return len(self.tokenizer)

    def encode(self, string: str) -> List[int]:
        # ASCII texts (the NQ hot path) take the C++ backend, whose semantics
        # are exactly the Python spec's on that domain; anything with
        # multibyte UTF-8 (accents, CJK) uses the full-Unicode Python path.
        # NUL also routes to Python: it cannot cross the C-string boundary,
        # and byte-level BPE (unlike WordPiece, which drops it) encodes byte 0
        # as a real token.
        if self._native is not None and string.isascii() and "\x00" not in string:
            return self._native.encode(string)
        return self.tokenizer.encode(string)

    def decode(self, ids, *, skip_special_tokens: bool = True) -> str:
        # The trailing ' ##' strip reproduces the reference wrapper's own
        # decode post-processing (tokenizer.py:61), applied on top of the
        # backend decode for BOTH models — it is a no-op for WordPiece output
        # but visibly rewrites byte-BPE decodes whose text contains ' ##'.
        return self.tokenizer.decode(
            ids, skip_special_tokens=skip_special_tokens
        ).replace(" ##", "")

    @property
    def pad_token_id(self) -> int:
        return self.tokenizer.token_to_id(self._pad_token)

    @property
    def sep_token_id(self) -> int:
        return self.tokenizer.token_to_id(self._sep_token)

    @property
    def cls_token_id(self) -> int:
        return self.tokenizer.token_to_id(self._cls_token)

    @property
    def unk_token_id(self) -> int:
        return self.tokenizer.token_to_id(self._unk_token)

    @property
    def pad_token(self) -> str:
        return self._pad_token

    @property
    def sep_token(self) -> str:
        return self._sep_token

    @property
    def cls_token(self) -> str:
        return self._cls_token

    @property
    def unk_token(self) -> str:
        return self._unk_token
