"""Vocab file utilities.

The reference assumes ``bert-base-uncased-vocab.txt`` was downloaded next to
the data (config/test_bert.cfg:4). This environment has no egress, so smoke
runs and benchmarks generate a synthetic vocab with the exact BERT layout:
[PAD]=0, [unused0..98]=1..99, [UNK]=100, [CLS]=101, [SEP]=102, [MASK]=103,
then filler wordpieces up to ``size``. Token *strings* are irrelevant for
DummyDataset runs — only ids and special-token positions matter.
"""

from __future__ import annotations

import os


def write_synthetic_bert_vocab(path, size: int = 30522) -> str:
    path = os.fspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tokens = ["[PAD]"]
    tokens += [f"[unused{i}]" for i in range(99)]
    tokens += ["[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    i = 0
    while len(tokens) < size:
        # mix whole words and continuations so chunking code sees both
        tokens.append(f"tok{i}" if i % 4 else f"##tok{i}")
        i += 1
    with open(path, "w") as fh:
        fh.write("\n".join(tokens[:size]) + "\n")
    return path
