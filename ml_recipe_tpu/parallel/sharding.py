"""Sharding rules and helpers.

Replaces the reference's replication-everywhere model (full replica params +
DistributedSampler data split, trainer.py:150-166) with explicit
`NamedSharding` layouts over the mesh:

- batches: leading (batch) dim over ``data``; optional sequence dim over
  ``seq`` for context parallelism;
- params: replicated by default; under tensor parallelism (``model`` axis)
  attention QKV / MLP kernels are sharded on the width dimension and the
  following projections on the input dimension, so each matmul stays local
  and XLA inserts the single reduce per block GSPMD-style.

``make_global_array`` assembles per-host numpy shards into one global
``jax.Array`` (the multi-host replacement for DistributedSampler: each host
feeds only its slice, SURVEY.md §7).
"""

from __future__ import annotations

import logging
import re
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"


# Tensor-parallel partition rules: (param-path regex -> PartitionSpec).
# Kernel shapes are [in, out]; embeddings [vocab, hidden].
TP_RULES = [
    (r".*attention/(query|key|value)/kernel$", P(None, MODEL_AXIS)),
    (r".*attention/(query|key|value)/bias$", P(MODEL_AXIS)),
    (r".*attention/output/kernel$", P(MODEL_AXIS, None)),
    (r".*mlp/intermediate/kernel$", P(None, MODEL_AXIS)),
    (r".*mlp/intermediate/bias$", P(MODEL_AXIS)),
    (r".*mlp/output/kernel$", P(MODEL_AXIS, None)),
]


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def param_pspecs(params, mesh: Mesh) -> dict:
    """PartitionSpec tree for a param tree: TP rules when the mesh has a
    ``model`` axis (>1), replicated otherwise."""
    has_tp = MODEL_AXIS in mesh.axis_names and mesh.shape[MODEL_AXIS] > 1

    def spec_for(path, leaf):
        if has_tp:
            path_s = _path_str(path)
            for pattern, spec in TP_RULES:
                if re.match(pattern, path_s):
                    return spec
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


class ZeroLeafPlan(NamedTuple):
    """Per-leaf ZeRO-1 placement: ``spec`` is the PartitionSpec of the
    (possibly padded) stored leaf; ``axis``/``padded`` name the dim carrying
    the ``data`` axis and its padded extent (``axis is None`` = replicated,
    ``padded == shape[axis]`` = no padding was needed)."""

    spec: P
    axis: Optional[int]
    padded: Optional[int]


# Param paths eligible for stage-local ``pipe``-axis sharding: exactly the
# leaves a pipeline stage consumes exclusively (the embedding table feeds
# only rank 0's refill; each encoder layer runs on exactly one stage).
# Pooler/head leaves run outside (or on the last tick of) the island on
# every rank's collected outputs, so they stay replicated — they are a
# rounding error of bert-large's bytes next to the layer stack.
STAGE_SCOPE_RE = re.compile(r"(^|/)transformer/(embeddings|layer_\d+)(/|$)")


def _zero_leaf_plan(path, shape, *, data_size: int,
                    has_tp: bool, min_size,
                    pipe_size: int = 1) -> ZeroLeafPlan:
    """The ONE dim chooser every ZeRO-1 consumer shares (state shardings,
    gradient constraints, byte modeling, checkpoint reconciliation):
    tensor-parallel axes are honored first; with ``pipe_size > 1`` the
    ``pipe`` axis then claims the largest stage-scope dim divisible by the
    stage count (stage-local param/optimizer storage — no padding: encoder
    dims are powers of two in practice, and a leaf with no dividing dim
    simply stays pipe-replicated); the ``data`` axis finally lands on
    the largest remaining dim already divisible by the axis size — or, when
    none divides, on the largest remaining dim PADDED up to the next
    multiple (this JAX rejects uneven shardings, so divisibility is bought
    with explicit zero padding of the stored state). Leaves below
    ``min_size`` elements (and scalars) stay replicated: sharding them buys
    nothing and costs collective latency."""
    axes = [None] * len(shape)
    path_s = _path_str(path)
    if has_tp:
        for pattern, spec in TP_RULES:
            if re.match(pattern, path_s):
                axes = list(spec) + [None] * (len(shape) - len(spec))
                break
    if pipe_size > 1 and STAGE_SCOPE_RE.search(path_s):
        pipe_free = [
            (dim, i) for i, dim in enumerate(shape)
            if axes[i] is None and dim % pipe_size == 0
        ]
        if pipe_free:
            _, i = max(pipe_free)
            axes[i] = PIPE_AXIS
    if data_size <= 1 or int(np.prod(shape or (0,))) < min_size:
        return ZeroLeafPlan(P(*axes), None, None)
    free = [(dim, i) for i, dim in enumerate(shape) if axes[i] is None]
    divisible = [(dim, i) for dim, i in free if dim % data_size == 0]
    if divisible:
        dim, i = max(divisible)
        padded = dim
    elif free and max(free)[0] >= 2:
        dim, i = max(free)
        padded = -(-dim // data_size) * data_size  # ceil to a multiple
    else:
        return ZeroLeafPlan(P(*axes), None, None)
    axes[i] = DATA_AXIS
    return ZeroLeafPlan(P(*axes), i, padded)


def zero1_plan(tree, mesh: Mesh, *, min_size: int = 16384,
               stage_pipe: bool = False):
    """ZeRO-1 placement plan for a (shape-carrying) pytree: one
    :class:`ZeroLeafPlan` per leaf. Works on live arrays and on
    ``jax.eval_shape`` outputs alike — only ``.shape`` is read. Leaf paths
    inside optax states end with the param path (e.g.
    ``.../mu/encoder/layer_0/attention/query/kernel``), so the tensor-
    parallel rules apply unchanged. With ``stage_pipe`` the ``pipe`` axis
    claims its stage-scope dim first, so the data-axis padded-leaf plan
    runs WITHIN a stage's leaf set (ZeRO-1 under pipeline)."""
    data_size = int(mesh.shape.get(DATA_AXIS, 1))
    has_tp = MODEL_AXIS in mesh.axis_names and mesh.shape[MODEL_AXIS] > 1
    pipe_size = (
        int(mesh.shape.get(PIPE_AXIS, 1)) if stage_pipe else 1
    )

    def plan_for(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        return _zero_leaf_plan(
            path, shape, data_size=data_size, has_tp=has_tp,
            min_size=min_size, pipe_size=pipe_size,
        )

    return jax.tree_util.tree_map_with_path(plan_for, tree)


def zero_pspecs(state_shapes, mesh: Mesh, *, min_size: int = 16384,
                stage_pipe: bool = False):
    """ZeRO-1 PartitionSpec tree for an optimizer-state (shape) tree.

    The reference replicates optimizer state on every replica (SURVEY.md
    §2.3 'full replica optimizer state'); here each moment tensor is sharded
    over the ``data`` axis so its memory scales 1/N with data parallelism —
    XLA all-gathers the (sharded) param updates it produces, which is the
    ZeRO-1 communication pattern. The specs assume the leaves are already at
    their PADDED extents (``zero_pad_tree``) where the plan demands padding.
    """
    return jax.tree_util.tree_map(
        lambda z: z.spec,
        zero1_plan(state_shapes, mesh, min_size=min_size,
                   stage_pipe=stage_pipe),
        is_leaf=lambda x: isinstance(x, ZeroLeafPlan),
    )


def zero_pad_tree(tree, plan):
    """Zero-pad each leaf along its plan axis up to the padded extent (the
    divisibility the ``data``-axis sharding needs). No-op leaves (plan axis
    None, or already divisible) pass through untouched — jnp.pad with a
    zero width is the identity, so the padded update step costs nothing on
    the (typical) leaves whose dims already divide."""

    def pad(x, z):
        if z.axis is None or z.padded == x.shape[z.axis]:
            return x
        widths = [(0, 0)] * x.ndim
        widths[z.axis] = (0, z.padded - x.shape[z.axis])
        return jnp.pad(x, widths)

    return jax.tree_util.tree_map(
        pad, tree, plan, is_leaf=lambda x: isinstance(x, ZeroLeafPlan)
    )


def zero_unpad_tree(tree, plan, logical):
    """Slice padded leaves back to the logical shapes of ``logical`` (a
    shape-carrying twin tree) — the inverse of :func:`zero_pad_tree`."""

    def unpad(x, z, ref):
        shape = tuple(ref.shape)
        if z.axis is None or tuple(x.shape) == shape:
            return x
        return jax.lax.slice(x, (0,) * x.ndim, shape)

    return jax.tree_util.tree_map(
        unpad, tree, plan, logical,
        is_leaf=lambda x: isinstance(x, ZeroLeafPlan),
    )


def opt_state_bytes_per_chip(opt_state) -> int:
    """MEASURED per-device resident bytes of a live optimizer-state tree:
    each leaf contributes one shard's bytes (its sharding's per-device
    shard shape), so a ZeRO-sharded state reports ~1/N of its replicated
    footprint. Host (numpy) leaves count in full — they are replicated by
    construction."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(opt_state):
        shape = tuple(np.shape(leaf))
        itemsize = np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            try:
                shape = tuple(sharding.shard_shape(shape))
            except Exception as e:  # noqa: BLE001 - exotic sharding
                logger.debug(
                    "shard_shape unavailable for %s (%s); counting the "
                    "full shape", type(sharding).__name__, e,
                )
        total += int(np.prod(shape or (1,), dtype=np.int64)) * itemsize
    return total


def zero1_state_bytes(state_shapes, *, data_size: int,
                      min_size: int = 16384,
                      pipe_size: int = 1) -> dict:
    """MODELED optimizer-state bytes per chip at an arbitrary data-axis
    size — no mesh, no devices, no compile: the HBM-planning probe
    (``bench.py --param_count_probe``) runs this before a TPU window opens.

    Returns ``replicated_bytes`` (every leaf in full — the historical
    layout), ``zero1_bytes`` (each plan-sharded leaf at its padded extent
    divided over ``data_size`` — and, with ``pipe_size > 1``, each
    stage-scope leaf further divided over its ``pipe`` dim — the rest in
    full) and ``sharded_bytes`` (the replicated footprint of exactly the
    leaves the plan shards — the ``(N-1)/N`` savings base the acceptance
    math is stated against).
    """
    data_size = max(1, int(data_size))
    pipe_size = max(1, int(pipe_size))

    def leaf_info(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = np.dtype(getattr(leaf, "dtype", np.float32))
        z = _zero_leaf_plan(
            path, shape, data_size=data_size, has_tp=False,
            min_size=min_size, pipe_size=pipe_size,
        )
        full = int(np.prod(shape or (1,), dtype=np.int64)) * dtype.itemsize
        shard = list(shape)
        for i, ax in enumerate(z.spec):
            if ax == PIPE_AXIS:
                shard[i] = shard[i] // pipe_size
        if z.axis is None:
            shard_bytes = (
                int(np.prod(shard or [1], dtype=np.int64)) * dtype.itemsize
            )
            sharded = full if shard_bytes < full else 0
            return full, shard_bytes, sharded
        shard[z.axis] = z.padded // data_size
        shard_bytes = int(np.prod(shard, dtype=np.int64)) * dtype.itemsize
        return full, shard_bytes, full

    infos = [
        leaf_info(path, leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(state_shapes)[0]
    ]
    return {
        "data_size": data_size,
        "replicated_bytes": sum(i[0] for i in infos),
        "zero1_bytes": sum(i[1] for i in infos),
        "sharded_bytes": sum(i[2] for i in infos),
    }


def leaf_sizes(tree):
    """Per-leaf element counts of ``tree`` in ``tree_leaves`` order — THE
    flattened-gradient layout every bucketed-overlap consumer shares.
    Bucket planning, the static slice offsets, and the train step's flat
    carry all derive from this one function: if they computed sizes
    independently and ever diverged (scalar-leaf handling, say), buckets
    would silently misalign and gradients would unflatten from wrong
    offsets with no error."""
    return [
        int(np.prod(l.shape)) if getattr(l, "ndim", 0) else 1
        for l in jax.tree_util.tree_leaves(tree)
    ]


def zero1_bucket_plan(params, *, bucket_mb: float):
    """Size-targeted gradient buckets over ``params``' flattened leaves
    (``--zero1_overlap bucketed``): each leaf contributes its f32
    ACCUMULATION footprint (gradients accumulate in f32 regardless of the
    param dtype), and contiguous runs close at ``bucket_mb``. The returned
    :class:`~.collectives.GradBucket` ranges index the same
    ``tree_leaves`` order the train step flattens with, so the bucket
    vectors concatenate to the monolithic flat gradient element for
    element."""
    from .collectives import plan_grad_buckets

    return plan_grad_buckets(
        leaf_sizes(params),
        bucket_bytes=max(1, int(float(bucket_mb) * 2**20)), itemsize=4,
    )


def is_single_device(mesh: Mesh) -> bool:
    """True when the mesh is one device — GSPMD placement is skipped entirely
    then: COMMITTED arrays (NamedSharding or explicit device) force a compile/
    dispatch path that is a measured ~120-200x slowdown on the tunneled
    single-chip 'axon' TPU backend, and buy nothing without peers."""
    return mesh.devices.size == 1


def put_single(x, mesh: Mesh):
    """Single-device placement that avoids committing when possible.

    Uncommitted device_put keeps the fast non-partitioned dispatch path; an
    explicit device target is only used when the mesh is pinned to a device
    other than the process default (where correctness requires commitment).
    """
    device = mesh.devices.flat[0]
    if device == jax.devices()[0]:
        return jax.device_put(x)
    return jax.device_put(x, device)


def shard_params(params, mesh: Mesh, pspecs: Optional[dict] = None):
    """Place a param tree onto the mesh with the given (or derived) specs."""
    if is_single_device(mesh):
        return jax.tree_util.tree_map(lambda x: put_single(x, mesh), params)
    if pspecs is None:
        pspecs = param_pspecs(params, mesh)
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)), params, pspecs
    )


def split_micro(tree, n: int):
    """Host ``[B, ...]`` leaves -> ``[n, B/n, ...]`` (micro-batch major) for
    the in-step gradient-accumulation scan. Shared by the Trainer and the
    device-prefetch placement thread — one definition of the micro layout."""

    def split(x):
        x = np.asarray(x)
        assert x.shape[0] % n == 0, (
            f"local batch {x.shape[0]} not divisible by batch_split {n}"
        )
        return x.reshape((n, x.shape[0] // n) + x.shape[1:])

    return jax.tree_util.tree_map(split, tree)


def batch_pspec(mesh: Mesh, *, shard_seq: bool = False, ndim: int = 2) -> P:
    """Spec for one batch leaf: batch dim over data, optionally seq dim over
    seq for context-parallel runs. Meshes without a data axis (e.g.
    ``pipe:2,model:2``) replicate the batch dim."""
    data_axis = DATA_AXIS if DATA_AXIS in mesh.axis_names else None
    seq_axis = (
        SEQ_AXIS
        if shard_seq and SEQ_AXIS in mesh.axis_names and mesh.shape[SEQ_AXIS] > 1
        else None
    )
    if ndim == 1:
        return P(data_axis)
    return P(data_axis, *([seq_axis] + [None] * (ndim - 2)))


def batch_sharding(mesh: Mesh, batch_tree, *, shard_seq: bool = False):
    """NamedSharding tree matching a (possibly nested) batch structure."""
    return jax.tree_util.tree_map(
        lambda x: NamedSharding(mesh, batch_pspec(mesh, shard_seq=shard_seq, ndim=np.ndim(x))),
        batch_tree,
    )


def make_global_array(
    host_batch, mesh: Mesh, *, shard_seq: bool = False, batch_axis: int = 0
):
    """Assemble per-host numpy shards into global jax.Arrays.

    Single-process: a plain sharded device_put. Multi-host: each process
    contributes its local rows (`jax.make_array_from_process_local_data`).
    ``batch_axis`` selects which dim is sharded over ``data`` (axis 1 for
    micro-batch-major [G, B, ...] layouts used by in-step grad accumulation).
    """
    if is_single_device(mesh):
        return jax.tree_util.tree_map(
            lambda x: put_single(np.asarray(x), mesh), host_batch
        )

    def to_global(x):
        x = np.asarray(x)
        if batch_axis == 0:
            spec = batch_pspec(mesh, shard_seq=shard_seq, ndim=x.ndim)
        else:
            axes = [None] * x.ndim
            if DATA_AXIS in mesh.axis_names:
                axes[batch_axis] = DATA_AXIS
            if (shard_seq and x.ndim > batch_axis + 1
                    and SEQ_AXIS in mesh.axis_names
                    and mesh.shape[SEQ_AXIS] > 1):
                # micro-batch-major [G, B, L]: the token dim after the
                # batch dim rides the seq axis, same as batch_pspec
                axes[batch_axis + 1] = SEQ_AXIS
            spec = P(*axes)
        sharding = NamedSharding(mesh, spec)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree_util.tree_map(to_global, host_batch)


def _local_cover_shards(x) -> Optional[dict]:
    """``{bounds: shard}`` for a de-duplicated set of addressable shards that
    covers every element of ``x``, or None when the local shards don't cover
    the array (i.e. some data lives only on other hosts)."""
    total = int(np.prod(x.shape, dtype=np.int64)) if x.shape else 1
    seen: dict = {}
    covered = 0
    for sh in x.addressable_shards:
        bounds = tuple(
            (int(s.start or 0), int(s.stop if s.stop is not None else dim))
            for s, dim in zip(sh.index, x.shape)
        )
        if bounds in seen:
            continue
        seen[bounds] = sh
        vol = int(np.prod([b - a for a, b in bounds], dtype=np.int64)) if bounds else 1
        covered += vol
    if covered != total:
        return None
    # volume-sum coverage is only sound if the de-duplicated bounds are
    # pairwise disjoint; overlapping-but-unequal index ranges would
    # double-count and leave unwritten np.empty garbage downstream. Not
    # producible with this repo's NamedShardings, but the helper is generic
    # over jax.Array (advisor r3).
    keys = list(seen)
    for i, a in enumerate(keys):
        for b in keys[i + 1:]:
            if all(a0 < b1 and b0 < a1 for (a0, a1), (b0, b1) in zip(a, b)):
                return None
    return seen


def local_host_copy(x) -> Optional[np.ndarray]:
    """Full host numpy copy of ``x`` assembled from addressable shards only —
    no collectives. Returns None when local shards don't cover the array.

    Replicated (and host-locally-sharded) arrays are fully reconstructable on
    every host, so gathering them never needs ``process_allgather``; that is
    what lets non-writing hosts skip checkpoint gathers entirely."""
    shards = _local_cover_shards(x)
    if shards is None:
        return None
    out = np.empty(x.shape, dtype=x.dtype)
    for bounds, sh in shards.items():
        idx = tuple(slice(a, b) for a, b in bounds)
        out[idx] = np.asarray(sh.data)
    return out


def needs_collective_gather(tree) -> bool:
    """True when gathering ``tree`` to host requires a cross-host collective
    (some leaf's data lives only on other hosts). With the standard symmetric
    NamedShardings every process computes the same answer, so it can gate who
    participates in :func:`gather_to_host`."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if (
            isinstance(leaf, jax.Array)
            and not leaf.is_fully_addressable
            and _local_cover_shards(leaf) is None
        ):
            return True
    return False


def gather_to_host(tree):
    """Device tree (possibly multi-host-sharded) -> full host numpy tree.

    Per-leaf strategy: fully-addressable -> plain device_get; replicated /
    locally-coverable -> assemble from addressable shards (no collective);
    genuinely cross-host-sharded -> ``process_allgather`` (collective — every
    process must call this function with the same tree)."""

    def gather(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            local = local_host_copy(x)
            if local is not None:
                return local
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(jax.device_get(x))

    return jax.tree_util.tree_map(gather, tree)
