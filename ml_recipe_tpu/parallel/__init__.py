from .mesh import MeshSpec, build_mesh, local_device_count
from .dist import (
    initialize_distributed,
    initialize_from_params,
    initialize_from_env,
    barrier,
    process_index,
    process_count,
    is_primary,
)
from .sharding import (
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    batch_pspec,
    batch_sharding,
    param_pspecs,
    shard_params,
    make_global_array,
    gather_to_host,
    TP_RULES,
)
from .collectives import pmean, psum_scalar, cross_replica_mean

__all__ = [
    "MeshSpec",
    "build_mesh",
    "local_device_count",
    "initialize_distributed",
    "initialize_from_params",
    "initialize_from_env",
    "barrier",
    "process_index",
    "process_count",
    "is_primary",
    "DATA_AXIS",
    "MODEL_AXIS",
    "SEQ_AXIS",
    "batch_pspec",
    "batch_sharding",
    "param_pspecs",
    "shard_params",
    "make_global_array",
    "gather_to_host",
    "TP_RULES",
    "pmean",
    "psum_scalar",
    "cross_replica_mean",
]
