"""Declarative parallelism plan — ONE source of truth for every layout.

The operator declares the topology once (``--mesh data:N,seq:M,pipe:K``)
and every consumer *derives* its shardings from the resulting
:class:`ParallelPlan` instead of hand-wiring per-leaf layouts:

- the trainer derives batch placement, param shardings, the ZeRO-1
  optimizer-state layout and the pipeline stage layout;
- the predictor and the serving engine derive batch placement;
- the HBM pre-flight and ``bench.py`` report ``plan.describe()`` and
  ``plan.unused_devices``;
- checkpoint manifests record ``mesh_axes`` so a restore knows what
  topology wrote them (reshard-on-restore stays shape-driven).

This is the TorchTitan discipline (arxiv 2410.06511): a single mesh +
per-feature sharding *derivation* is what makes 3D/4D parallelism
composable instead of five parallel rewirings. graftlint rule MLA009
enforces the flip side: no ``NamedSharding``/``PartitionSpec``
construction outside ``parallel/``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import MeshSpec, build_mesh, elastic_axes, unused_device_count
from .sharding import (
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    ZeroLeafPlan,
    batch_pspec,
    batch_sharding,
    is_single_device,
    param_pspecs,
    zero1_plan,
    zero_pspecs,
)


@dataclasses.dataclass(frozen=True)
class ParallelPlan:
    """The declarative mesh plan: a named mesh plus derivation methods.

    Construction: :meth:`from_spec` (the ``--mesh`` string) or
    :meth:`from_mesh` (an already-built mesh). Both record how many
    visible devices the mesh leaves stranded.
    """

    mesh: Mesh
    unused_devices: int = 0
    # the axes the OPERATOR asked for (--mesh), recorded by
    # elastic_from_spec so `shrunk` can report a topology change; None for
    # plans built by the fixed-world constructors
    requested_axes: Optional[Dict[str, int]] = None

    # -- construction --------------------------------------------------------

    @classmethod
    def from_spec(cls, spec: Optional[str] = None, *,
                  devices: Optional[Sequence] = None) -> "ParallelPlan":
        mesh = build_mesh(spec, devices=devices)
        return cls(mesh=mesh, unused_devices=unused_device_count(mesh))

    @classmethod
    def from_mesh(cls, mesh: Mesh) -> "ParallelPlan":
        return cls(mesh=mesh, unused_devices=unused_device_count(mesh))

    @classmethod
    def elastic_from_spec(cls, spec: Optional[str] = None, *,
                          devices: Optional[Sequence] = None,
                          min_data: int = 1) -> "ParallelPlan":
        """``from_spec`` that SHRINKS instead of raising when the requested
        mesh no longer fits the live device set (``--elastic on``): only
        the data axis narrows (``mesh.elastic_axes``), structural axes
        refuse loudly. Records the original request so ``shrunk`` (and the
        mesh_shrunk flight-recorder event) can report the change."""
        devices = list(devices if devices is not None else jax.devices())
        requested = MeshSpec.from_string(spec, n_devices=len(devices)).ordered()
        axes = elastic_axes(requested, len(devices), min_data=min_data)
        mesh = build_mesh(devices=devices, axes=axes)
        return cls(
            mesh=mesh,
            unused_devices=unused_device_count(mesh),
            requested_axes=dict(requested),
        )

    @property
    def shrunk(self) -> bool:
        """True when this plan was elastically narrowed below the operator's
        requested topology (always False for fixed-world plans)."""
        return (
            self.requested_axes is not None
            and self.requested_axes != self.describe()
        )

    # -- topology ------------------------------------------------------------

    def axis_size(self, name: str) -> int:
        """Size of a mesh axis; 1 when the axis is absent (the identity
        for every layout derivation — an absent axis shards nothing)."""
        return int(self.mesh.shape.get(name, 1))

    @property
    def data_size(self) -> int:
        return self.axis_size(DATA_AXIS)

    @property
    def seq_size(self) -> int:
        return self.axis_size(SEQ_AXIS)

    @property
    def model_size(self) -> int:
        return self.axis_size(MODEL_AXIS)

    @property
    def pipe_size(self) -> int:
        return self.axis_size(PIPE_AXIS)

    @property
    def single_device(self) -> bool:
        return is_single_device(self.mesh)

    def describe(self) -> Dict[str, int]:
        """``{axis: size}`` in mesh order — the spelling manifests, the
        pre-flight report and bench JSON all record."""
        return {
            str(name): int(size)
            for name, size in zip(self.mesh.axis_names, self.mesh.devices.shape)
        }

    def stage_map(self, num_layers: int) -> Dict[str, str]:
        """``{"stage_k": "layer_lo..layer_hi"}`` — which contiguous encoder
        layers each pipe rank owns (pre-flight report / bench JSON). Empty
        when the plan has no multi-way pipe axis."""
        if self.pipe_size <= 1:
            return {}
        from .pipeline import stage_assignment

        return {
            f"stage_{k}": f"layer_{lo}..layer_{hi - 1}"
            for k, (lo, hi) in stage_assignment(
                int(num_layers), self.pipe_size
            ).items()
        }

    def stage_specs(self, params):
        """Stage-local param PartitionSpec tree (trunk leaves over
        ``pipe``, TP dims honored) — see ``pipeline.stage_param_specs``."""
        from .pipeline import stage_param_specs

        return stage_param_specs(params, self)

    # -- derived shardings ---------------------------------------------------

    def named(self, spec: P) -> NamedSharding:
        """A NamedSharding over this plan's mesh. The one constructor
        call sites outside ``parallel/`` go through (MLA009)."""
        return NamedSharding(self.mesh, spec)

    def replicated(self) -> NamedSharding:
        return self.named(P())

    def put_replicated(self, tree):
        """Place a host tree fully replicated over the mesh."""
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self.replicated()), tree
        )

    def batch_spec(self, *, shard_seq: bool = False, ndim: int = 2) -> P:
        return batch_pspec(self.mesh, shard_seq=shard_seq, ndim=ndim)

    def batch_shardings(self, batch_tree, *, shard_seq: bool = False):
        return batch_sharding(self.mesh, batch_tree, shard_seq=shard_seq)

    def param_specs(self, params):
        return param_pspecs(params, self.mesh)

    def zero1(self, tree, *, min_size: int = 16384,
              stage_pipe: bool = False):
        """The padding-aware per-leaf ZeRO-1 placement plan (over the
        ``data`` axis; TP axes honored; with ``stage_pipe`` the ``pipe``
        axis claims its stage-scope dim first, so the data-axis plan runs
        within each stage's leaf set) — see ``sharding.zero1_plan``."""
        return zero1_plan(tree, self.mesh, min_size=min_size,
                          stage_pipe=stage_pipe)

    def zero1_param_shardings(self, zplan):
        """NamedSharding tree for a ZeRO-1 leaf-plan tree (the layout the
        padded grads/params are constrained onto inside the train step)."""
        return jax.tree_util.tree_map(
            lambda z: self.named(z.spec), zplan,
            is_leaf=lambda x: isinstance(x, ZeroLeafPlan),
        )

    def opt_state_shardings(self, state_shapes, *,
                            zero1: bool, min_size: int = 16384,
                            stage_pipe: bool = False):
        """NamedSharding tree for an optimizer-state (shape) tree:
        ZeRO-1 layout when ``zero1`` (each shardable leaf over ``data``),
        otherwise the replicated-with-TP-rules layout; ``stage_pipe``
        additionally lands each stage-scope leaf's moments on the
        ``pipe`` axis (stage-local optimizer state — independent of the
        min_size gate, which only governs the data axis). ONE derivation
        for the trainer's ``init_opt_state``, the checkpoint
        reconciliation and the layout-consistency tests."""
        return jax.tree_util.tree_map(
            lambda spec: self.named(spec),
            zero_pspecs(
                state_shapes, self.mesh,
                # min_size=inf disables the data axis: TP rules still
                # apply, everything else replicates (the non-ZeRO layout)
                min_size=min_size if zero1 else math.inf,
                stage_pipe=stage_pipe,
            ),
        )

