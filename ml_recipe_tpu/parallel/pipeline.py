"""Pipeline parallelism over the ``pipe`` mesh axis (GPipe schedule).

The encoder's layer stack is partitioned into K contiguous stages placed
on the ``pipe`` mesh dimension; the ``batch_split`` micro-batches (the
SAME micro split the gradient-accumulation scan uses,
``sharding.split_micro``) stream through the stages on a GPipe schedule:
at tick t, stage k runs micro-batch ``t - k``, so stage k's forward on
micro-batch i overlaps stage k+1's forward on micro-batch i-1. The whole
schedule is ONE ``shard_map`` island inside the jitted train step:

- each pipe rank executes only its own stage's contiguous layers per
  tick (``lax.switch`` on the rank index; params stay replicated);
- the per-tick activation hand-off to the next rank is a literal
  ``lax.ppermute`` over the ``pipe`` axis — activations cross stage
  boundaries point-to-point; rank 0 refills from the next micro-batch;
- the backward pass is plain autodiff through the tick scan: the
  ppermute transposes to the reverse permute, giving the mirrored
  backward pipeline for free, and gradients accumulate across
  micro-batches exactly as the sequential scan does (grad of the summed
  micro losses == the summed micro grads), pinning the arithmetic
  against the single-axis run.

Schedule accounting: with K stages and m micro-batches the loop runs
``m + K - 1`` ticks of which only ``m`` are useful per stage — the GPipe
bubble fraction ``(K-1)/(K-1+m)`` (arxiv 1811.06965; MPMD pipelining,
arxiv 2412.14374). :func:`modeled_bubble_fraction` /
:func:`measured_bubble_fractions` are the bench's efficiency instrument.
"""

from __future__ import annotations

import functools
import logging
from typing import Dict, Mapping

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)


# -- schedule accounting -----------------------------------------------------

def modeled_bubble_fraction(stages: int, microbatches: int) -> float:
    """GPipe bubble: the fraction of schedule ticks a stage spends idle,
    ``(K-1)/(K-1+m)``. 0 for a single stage."""
    stages = int(stages)
    microbatches = max(1, int(microbatches))
    if stages <= 1:
        return 0.0
    return (stages - 1) / (stages - 1 + microbatches)


def measured_bubble_fractions(
    step_times: Mapping[int, float], stages: int
) -> Dict[int, float]:
    """Measured bubble per micro-batch count from a step-time sweep.

    Each measurement at m micro-batches estimates the ideal (bubble-free)
    step time as ``T(m) * m / (m + K - 1)`` — under the GPipe model these
    estimates agree across the sweep, so their median is the reference
    ideal, and ``1 - ideal / T(m)`` is the measured bubble. A schedule
    with NO real overlap (sequential stages) yields a near-constant
    measured fraction instead of the decreasing ``(K-1)/(K-1+m)`` curve,
    which is what the bench sweep (and its test) pins against.
    """
    stages = int(stages)
    if stages <= 1 or not step_times:
        return {int(m): 0.0 for m in step_times}
    ideal = float(np.median([
        t * m / (m + stages - 1) for m, t in step_times.items()
    ]))
    return {
        int(m): max(0.0, 1.0 - ideal / float(t))
        for m, t in step_times.items()
    }


def stage_layer_count(num_layers: int, stages: int) -> int:
    """Layers per stage; the stack must split into K EQUAL contiguous
    stages (unequal stages would make the slowest stage the tick clock
    and silently waste the rest)."""
    stages = int(stages)
    if stages < 1:
        raise ValueError(f"pipe axis size must be >= 1, got {stages}")
    if num_layers % stages != 0:
        raise ValueError(
            f"--mesh pipe:{stages} needs the encoder depth to split into "
            f"equal contiguous stages, but {num_layers} layers % {stages} "
            f"!= 0; choose a pipe size dividing num_layers"
        )
    return num_layers // stages


def validate_pipeline_plan(plan, model, *, batch_split: int) -> None:
    """Fail at construction (not at trace time) on configurations the
    pipeline runtime does not compose with yet."""
    cfg = getattr(model, "cfg", None)
    if cfg is None or not hasattr(cfg, "num_layers"):
        raise ValueError(
            "pipeline parallelism needs a layered encoder model "
            "(model.cfg.num_layers); got a model without one"
        )
    stage_layer_count(cfg.num_layers, plan.pipe_size)
    if plan.seq_size > 1:
        raise NotImplementedError(
            "--mesh with both seq (ring attention) and pipe axes is not "
            "composable yet: ring's shard_map cannot nest inside the "
            "vmapped stage compute"
        )
    if plan.model_size > 1:
        raise NotImplementedError(
            "--mesh with both model (tensor parallel) and pipe axes is "
            "not composable yet: stage-stacked layer params drop the TP "
            "dim specs"
        )
    if batch_split < 1:
        raise ValueError(f"batch_split must be >= 1, got {batch_split}")


# -- pipelined encoder forward ----------------------------------------------

def make_pipeline_encoder(model, plan, *, batch_split: int,
                          deterministic: bool,
                          prng_impl: str = "threefry2x32"):
    """Build ``encode(params, micro_inputs, base_key) -> (seq_out,
    pooled)`` running the encoder trunk on the GPipe schedule.

    ``params`` is the full (replicated) QAModel param tree;
    ``micro_inputs`` the ``[G, B_micro, ...]`` micro-split input planes
    the gradient-accumulation scan already uses (rows sharded over
    ``data`` on dim 1). Outputs are ``[G, B_micro, L, H]`` sequence
    states and ``[G, B_micro, (S,) H]`` pooled vectors — the QA heads
    and the loss run on them exactly as on the sequential path.

    The schedule is an EXPLICIT ``shard_map`` over the ``pipe`` axis
    (MPMD discipline, arxiv 2412.14374): each pipe rank runs only its
    own stage's layers per tick (``lax.switch`` on the rank index), the
    per-tick activation hand-off is a literal ``lax.ppermute`` to the
    next rank, and the collected last-stage outputs come back through
    one masked psum. Nothing is left to the auto-partitioner's choices —
    on the virtual CPU mesh, GSPMD's resharding of in-jit-stacked
    replicated params onto a ``pipe``-sharded layout was observed to
    MISCOMPUTE (see tests/test_parallel_plan.py parity pins), which is
    exactly the class of silent wrongness the explicit formulation
    removes. Rank 0 also evaluates the (cheap) embedding refill every
    tick; other ranks discard it, so its gradient flows only once.

    Dropout keys are pure functions of (base_key, micro index, global
    layer index): deterministic and resume-stable, but a DIFFERENT
    stream than the sequential path's flax module-path folding —
    pipeline trajectories are pinned against single-axis runs with
    dropout off (reduction-order tolerance), matching the DDP precedent
    that never promised cross-topology dropout determinism.
    """
    import flax.linen as nn
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..models.encoder import Embeddings, EncoderLayer, _dense
    from .sharding import DATA_AXIS, PIPE_AXIS

    cfg = model.cfg
    mesh = plan.mesh
    K = int(plan.pipe_size)
    G = int(batch_split)
    S = stage_layer_count(cfg.num_layers, K)
    T = G + K - 1

    emb_mod = Embeddings(cfg, model.dtype, model.ln_impl)
    layer_cls = EncoderLayer
    if model.remat:
        layer_cls = nn.remat(EncoderLayer, static_argnums=(3,))
    layer_mod = layer_cls(cfg, model.dtype, model.attention_impl,
                          model.mesh, model.ln_impl, quantize=model.quantize)
    pooler_mod = _dense(model.quantize, cfg.hidden_size, name="pooler",
                        dtype=model.dtype)

    def encode(params, micro_inputs, base_key):
        t_params = params["transformer"]
        seg_starts = micro_inputs.get("segment_starts")
        has_seg = micro_inputs.get("segment_ids") is not None
        planes = {
            k: micro_inputs[k]
            for k in ("input_ids", "attention_mask", "token_type_ids",
                      "position_ids", "segment_ids")
            if micro_inputs.get(k) is not None
        }
        if "attention_mask" not in planes:
            planes["attention_mask"] = jnp.ones_like(planes["input_ids"])
        if "token_type_ids" not in planes:
            planes["token_type_ids"] = jnp.zeros_like(planes["input_ids"])
        # keys cross the shard_map boundary as raw uint32 data (extended
        # key dtypes through shard_map are version-fragile)
        kd = jax.random.key_data(base_key)

        def body(t_params, planes, kd):
            k_idx = jax.lax.axis_index(PIPE_AXIS)
            is_first = k_idx == 0
            base = jax.random.wrap_key_data(kd, impl=prng_impl)
            input_ids = planes["input_ids"]
            mask = planes["attention_mask"]
            ttype = planes["token_type_ids"]
            pos_ids = planes.get("position_ids")
            seg_ids = planes.get("segment_ids")
            B, Lseq = input_ids.shape[1], input_ids.shape[2]

            def micro_key(i):
                # i runs out of [0, G) on warmup/drain lanes — those keys
                # (and the activations they drop) are garbage that never
                # reaches a collected output
                return jax.random.fold_in(base, i)

            def take(x, i, *, keep=False):
                return jax.lax.dynamic_index_in_dim(
                    x, jnp.clip(i, 0, G - 1), 0, keepdims=keep
                )

            def embed_micro(i):
                return emb_mod.apply(
                    {"params": t_params["embeddings"]},
                    take(input_ids, i), take(ttype, i),
                    deterministic=deterministic,
                    position_ids=(
                        None if pos_ids is None else take(pos_ids, i)
                    ),
                    rngs={"dropout": jax.random.fold_in(micro_key(i), 0)},
                )

            def run_stage(kk, h, m, sg, micro_idx):
                # stage kk = contiguous layers [kk*S, (kk+1)*S)
                for s in range(S):
                    li = kk * S + s
                    key_l = jax.random.fold_in(
                        micro_key(micro_idx), 1 + li
                    )
                    h = layer_mod.apply(
                        {"params": t_params[f"layer_{li}"]}, h, m,
                        deterministic, sg if has_seg else None,
                        rngs={"dropout": key_l},
                    )
                return h

            def stage(h, m, sg, micro_idx):
                # each rank executes exactly ONE branch — its own stage
                branches = [
                    functools.partial(run_stage, kk) for kk in range(K)
                ]
                return jax.lax.switch(k_idx, branches, h, m, sg, micro_idx)

            h0 = embed_micro(jnp.int32(0))
            h = jnp.where(is_first, h0, jnp.zeros_like(h0))
            m = jnp.where(is_first, take(mask, jnp.int32(0)),
                          jnp.zeros_like(mask[0]))
            # the segment plane rides the rotation as a dummy when
            # packing is off (one [B, L] int buffer — cheap) so the
            # carry/switch structure is static
            seg_src = seg_ids if has_seg else mask
            sg = jnp.where(is_first, take(seg_src, jnp.int32(0)),
                           jnp.zeros_like(seg_src[0]))
            out0 = jnp.zeros((G, B, Lseq, int(cfg.hidden_size)), h0.dtype)
            perm = [(i, (i + 1) % K) for i in range(K)]

            def tick(carry, t):
                h, m, sg, out = carry
                micro_idx = t - k_idx
                y = stage(h, m, sg, micro_idx)
                # collect the LAST stage's output. Before tick K-1 the
                # write lands (clipped) on slot 0 with warmup garbage —
                # tick K-1 overwrites it with micro-batch 0's true
                # output, and every later slot is written exactly once
                # at its true tick, so no per-tick select is needed
                slot = jnp.clip(t - (K - 1), 0, G - 1)
                out = jax.lax.dynamic_update_slice(
                    out, y[None].astype(out.dtype), (slot, 0, 0, 0)
                )
                # the stage-boundary hand-off: activations (and their
                # mask/segment planes) cross to rank k+1 via collective
                # permute; rank 0 refills from the next micro-batch
                nxt = t + 1
                y_n = jax.lax.ppermute(y, PIPE_AXIS, perm)
                m_n = jax.lax.ppermute(m, PIPE_AXIS, perm)
                sg_n = jax.lax.ppermute(sg, PIPE_AXIS, perm)
                h = jnp.where(is_first, embed_micro(nxt).astype(y_n.dtype),
                              y_n)
                m = jnp.where(is_first, take(mask, nxt), m_n)
                sg = jnp.where(is_first, take(seg_src, nxt), sg_n)
                return (h, m, sg, out), None

            (_, _, _, out), _ = jax.lax.scan(
                tick, (h, m, sg, out0), jnp.arange(T, dtype=jnp.int32)
            )
            # only rank K-1 collected real outputs; the masked psum is
            # the one gather that returns them to every rank
            out = out * (k_idx == K - 1).astype(out.dtype)
            return jax.lax.psum(out, PIPE_AXIS)

        seq_out = shard_map(
            body, mesh,
            in_specs=(P(), P(None, DATA_AXIS, None), P()),
            out_specs=P(None, DATA_AXIS, None, None),
            check_rep=False,
        )(t_params, planes, kd)

        # pooled output — the encoder tail (encoder.py): each row's [CLS]
        # (or each packed segment's own [CLS]) through the pooler Dense;
        # plain data-parallel compute outside the pipeline island
        if seg_starts is None:
            pool_src = seq_out[:, :, 0]
        else:
            pool_src = jnp.take_along_axis(
                seq_out, seg_starts[..., None].astype(jnp.int32), axis=2
            )
        pooled = jnp.tanh(
            pooler_mod.apply({"params": t_params["pooler"]}, pool_src)
        )
        return seq_out, pooled

    return encode


def apply_qa_heads(model, params, sequence_output, pooled_output,
                   attention_mask, *, deterministic, dropout_rng,
                   segment_ids=None, segment_starts=None):
    """The QA heads on ONE micro-batch of (pipelined) encoder outputs —
    mirrors the post-trunk body of ``QAModel.__call__`` (span logits with
    pad masking, per-segment confinement when packed, classifier on the
    dropped-out pooled vector, sigmoid regressors). Parameters are the
    same head leaves, so the two paths are interchangeable; parity with
    the sequential forward is pinned in tests/test_parallel_plan.py.
    """
    from ..models.encoder import _dense
    from ..models.qa_model import _MASK_NEG
    import flax.linen as nn

    cfg = model.cfg
    packed = segment_starts is not None

    position_logits = _dense(
        model.quantize, 2, name="position_outputs", dtype=model.dtype
    ).apply({"params": params["position_outputs"]}, sequence_output)
    start_logits = position_logits[..., 0]
    end_logits = position_logits[..., 1]

    pad_penalty = (1 - attention_mask).astype(jnp.float32) * _MASK_NEG
    start_logits = start_logits.astype(jnp.float32) + pad_penalty
    end_logits = end_logits.astype(jnp.float32) + pad_penalty

    if packed:
        S = segment_starts.shape[1]
        seg_eq = (
            segment_ids[:, None, :]
            == (1 + jnp.arange(S, dtype=segment_ids.dtype))[None, :, None]
        )
        seg_penalty = jnp.where(seg_eq, 0.0, jnp.float32(_MASK_NEG))
        start_logits = start_logits[:, None, :] + seg_penalty
        end_logits = end_logits[:, None, :] + seg_penalty

    cls_hidden = nn.Dropout(cfg.hidden_dropout_prob).apply(
        {}, pooled_output, deterministic=deterministic,
        rngs={"dropout": dropout_rng},
    )
    classifier_logits = _dense(
        model.quantize, cfg.num_labels, name="classifier", dtype=model.dtype
    ).apply({"params": params["classifier"]}, cls_hidden)

    reg_start = nn.sigmoid(
        _dense(model.quantize, 1, name="reg_start", dtype=model.dtype)
        .apply({"params": params["reg_start"]}, pooled_output)
    )[..., 0]
    reg_end = nn.sigmoid(
        _dense(model.quantize, 1, name="reg_end", dtype=model.dtype)
        .apply({"params": params["reg_end"]}, pooled_output)
    )[..., 0]

    return {
        "start_class": start_logits,
        "end_class": end_logits,
        "start_reg": reg_start.astype(jnp.float32),
        "end_reg": reg_end.astype(jnp.float32),
        "cls": classifier_logits.astype(jnp.float32),
    }
