"""Pipeline parallelism over the ``pipe`` mesh axis (GPipe and 1F1B).

The encoder's layer stack is partitioned into K contiguous stages placed
on the ``pipe`` mesh dimension; the ``batch_split`` micro-batches (the
SAME micro split the gradient-accumulation scan uses,
``sharding.split_micro``) stream through the stages on a GPipe schedule:
at tick t, stage k runs micro-batch ``t - k``, so stage k's forward on
micro-batch i overlaps stage k+1's forward on micro-batch i-1. The whole
schedule is ONE ``shard_map`` island inside the jitted train step:

- each pipe rank executes only its own stage's contiguous layers per
  tick (``lax.switch`` on the rank index; params stay replicated);
- the per-tick activation hand-off to the next rank is a literal
  ``lax.ppermute`` over the ``pipe`` axis — activations cross stage
  boundaries point-to-point; rank 0 refills from the next micro-batch;
- the backward pass is plain autodiff through the tick scan: the
  ppermute transposes to the reverse permute, giving the mirrored
  backward pipeline for free, and gradients accumulate across
  micro-batches exactly as the sequential scan does (grad of the summed
  micro losses == the summed micro grads), pinning the arithmetic
  against the single-axis run.

Schedule accounting: with K stages and m micro-batches the GPipe loop
runs ``m + K - 1`` ticks of which only ``m`` are useful per stage — the
GPipe bubble fraction ``(K-1)/(K-1+m)`` (arxiv 1811.06965; MPMD
pipelining, arxiv 2412.14374). The 1F1B schedule
(:func:`make_pipeline_train_step`) interleaves one backward per forward
so a stage holds at most ``min(m, 2K-1)`` in-flight activations instead
of all m, at a ``(2K-2)/(m+2K-2)`` bubble over its combined
forward+backward tick program (TorchTitan schedules, arxiv 2410.06511).
:func:`modeled_bubble_fraction` / :func:`measured_bubble_fractions` are
the bench's efficiency instrument for both.

Stage-local state: :func:`stage_param_specs` shards each stage-scope
param leaf (embeddings + encoder layers) over ``pipe`` on a free dim, so
per-chip param/optimizer bytes drop ~1/K; the islands take the sharded
leaves as ``shard_map`` in_specs and reassemble them with EXPLICIT
``lax.all_gather`` calls (never GSPMD boundary resharding, which was
observed to miscompute on the CPU mesh).
"""

from __future__ import annotations

import functools
import logging
import re
from typing import Dict, Mapping

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)


# -- schedule accounting -----------------------------------------------------

PIPE_SCHEDULES = ("gpipe", "1f1b")


def _schedule_overhead_ticks(stages: int, schedule: str) -> int:
    """Idle ticks a stage sees beyond its m useful ones: ``K-1`` warmup
    lanes for GPipe's forward program, ``2(K-1)`` (warmup + drain) for
    1F1B's combined forward+backward program."""
    if schedule not in PIPE_SCHEDULES:
        raise ValueError(
            f"unknown pipe schedule {schedule!r}; choose one of "
            f"{PIPE_SCHEDULES}"
        )
    return (stages - 1) if schedule == "gpipe" else 2 * (stages - 1)


def modeled_bubble_fraction(stages: int, microbatches: int,
                            schedule: str = "gpipe") -> float:
    """Pipeline bubble: the fraction of schedule ticks a stage spends
    idle — ``(K-1)/(K-1+m)`` for GPipe, ``(2K-2)/(2K-2+m)`` for 1F1B
    (whose tick program covers forward AND backward, so warmup and drain
    both count). 0 for a single stage."""
    stages = int(stages)
    microbatches = max(1, int(microbatches))
    c = _schedule_overhead_ticks(stages, schedule)
    if stages <= 1:
        return 0.0
    return c / (c + microbatches)


def measured_bubble_fractions(
    step_times: Mapping[int, float], stages: int,
    schedule: str = "gpipe",
) -> Dict[int, float]:
    """Measured bubble per micro-batch count from a step-time sweep.

    Each measurement at m micro-batches estimates the ideal (bubble-free)
    step time as ``T(m) * m / (m + c)`` with ``c`` the schedule's
    overhead ticks (``K-1`` GPipe, ``2(K-1)`` 1F1B) — under the schedule
    model these estimates agree across the sweep, so their median is the
    reference ideal, and ``1 - ideal / T(m)`` is the measured bubble. A
    schedule with NO real overlap (sequential stages) yields a
    near-constant measured fraction instead of the decreasing modeled
    curve, which is what the bench sweep (and its test) pins against.
    """
    stages = int(stages)
    c = _schedule_overhead_ticks(max(stages, 1), schedule)
    if stages <= 1 or not step_times:
        return {int(m): 0.0 for m in step_times}
    ideal = float(np.median([
        t * m / (m + c) for m, t in step_times.items()
    ]))
    return {
        int(m): max(0.0, 1.0 - ideal / float(t))
        for m, t in step_times.items()
    }


def stage_layer_count(num_layers: int, stages: int) -> int:
    """Layers per stage; the stack must split into K EQUAL contiguous
    stages (unequal stages would make the slowest stage the tick clock
    and silently waste the rest)."""
    stages = int(stages)
    if stages < 1:
        raise ValueError(f"pipe axis size must be >= 1, got {stages}")
    if num_layers % stages != 0:
        raise ValueError(
            f"--mesh pipe:{stages} needs the encoder depth to split into "
            f"equal contiguous stages, but {num_layers} layers % {stages} "
            f"!= 0; choose a pipe size dividing num_layers"
        )
    return num_layers // stages


def validate_pipeline_plan(plan, model, *, batch_split: int,
                           schedule: str = "gpipe") -> None:
    """Fail at construction (not at trace time) on configurations the
    pipeline runtime does not compose with yet. ``pipe x model`` IS
    composable (stage specs keep their TP dims; the island all-gathers
    both axes explicitly); ``pipe x seq`` is not."""
    cfg = getattr(model, "cfg", None)
    if cfg is None or not hasattr(cfg, "num_layers"):
        raise ValueError(
            "pipeline parallelism needs a layered encoder model "
            "(model.cfg.num_layers); got a model without one"
        )
    stage_layer_count(cfg.num_layers, plan.pipe_size)
    if schedule not in PIPE_SCHEDULES:
        raise ValueError(
            f"--pipe_schedule must be one of {PIPE_SCHEDULES}, "
            f"got {schedule!r}"
        )
    if plan.seq_size > 1:
        raise NotImplementedError(
            "--mesh with both seq and pipe axes is not composable yet: "
            "the composed streaming-ring attention path (ISSUE 20, "
            "ops/ring_attention.py) runs under its own shard_map, which "
            "cannot nest inside the pipeline island's per-tick stage "
            "compute (one shard_map cannot contain the other's "
            "collectives). Follow-up: host the ring hop loop inside the "
            "stage body so the pipe island owns both collectives."
        )
    if batch_split < 1:
        raise ValueError(f"batch_split must be >= 1, got {batch_split}")


# -- stage-local parameter layout --------------------------------------------

def stage_assignment(num_layers: int, stages: int) -> Dict[int, tuple]:
    """``{stage: (first_layer, last_layer_exclusive)}`` — which contiguous
    encoder layers each pipe rank owns. Embeddings ride with stage 0 (the
    refill rank); pooler/heads with stage K-1 (the collecting rank)."""
    S = stage_layer_count(num_layers, stages)
    return {k: (k * S, (k + 1) * S) for k in range(int(stages))}


def stage_param_specs(params, plan):
    """PartitionSpec tree sharding each stage-scope leaf (embeddings +
    encoder layers) over the ``pipe`` axis so every rank STORES ~1/K of
    the trunk — the pipeline's missing memory win. TP dims are claimed
    first (``pipe x model`` keeps its tensor-parallel specs); the pipe
    axis then lands on the leaf's largest remaining dim divisible by K
    (:func:`~.sharding._zero_leaf_plan`, the shared dim chooser, with
    ``data_size=1`` — ZeRO-1's data-axis plan is layered separately so
    it runs WITHIN the stage-local leaf set). Pooler/head leaves stay
    replicated: they run on the collected outputs outside the trunk and
    are noise next to the layer stack's bytes."""
    from .sharding import _zero_leaf_plan

    pipe_size = int(plan.pipe_size)
    has_tp = plan.model_size > 1

    def spec_for(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        return _zero_leaf_plan(
            path, shape, data_size=1, has_tp=has_tp, min_size=0,
            pipe_size=pipe_size,
        ).spec

    return jax.tree_util.tree_map_with_path(spec_for, params)


def stage_param_bytes(params, *, pipe_size: int,
                      model_size: int = 1) -> dict:
    """MODELED per-chip param bytes under the stage-local layout — no
    mesh, no devices: ``replicated_bytes`` (every leaf in full, the
    pre-stage-sharding layout), ``per_chip_bytes`` (stage-scope leaves at
    1/K — and TP leaves at 1/T — the rest in full), and ``per_stage_bytes``
    (``{stage: bytes}`` in the ownership view: embeddings with stage 0,
    each layer with its owner, pooler/heads with stage K-1) for the
    pre-flight report's stage map."""
    from .sharding import (
        MODEL_AXIS, PIPE_AXIS, STAGE_SCOPE_RE, _path_str, _zero_leaf_plan,
    )

    pipe_size = max(1, int(pipe_size))
    model_size = max(1, int(model_size))
    num_layers = len([
        k for k in params.get("transformer", {}) if k.startswith("layer_")
    ])
    owners = {}
    if num_layers and pipe_size > 1:
        for k, (lo, hi) in stage_assignment(num_layers, pipe_size).items():
            for li in range(lo, hi):
                owners[f"layer_{li}"] = k

    replicated = 0
    per_chip = 0
    per_stage = {k: 0 for k in range(pipe_size)}
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    for path, leaf in flat:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = np.dtype(getattr(leaf, "dtype", np.float32))
        full = int(np.prod(shape or (1,), dtype=np.int64)) * dtype.itemsize
        replicated += full
        spec = _zero_leaf_plan(
            path, shape, data_size=1, has_tp=model_size > 1, min_size=0,
            pipe_size=pipe_size,
        ).spec
        shard = full
        for i, ax in enumerate(spec):
            if ax == PIPE_AXIS:
                shard //= pipe_size
            elif ax == MODEL_AXIS:
                shard //= model_size
        per_chip += shard
        path_s = _path_str(path)
        m = re.search(r"(^|/)transformer/(layer_\d+)(/|$)", path_s)
        if m and m.group(2) in owners:
            per_stage[owners[m.group(2)]] += full
        elif STAGE_SCOPE_RE.search(path_s):
            per_stage[0] += full  # embeddings feed rank 0's refill
        else:
            per_stage[pipe_size - 1] += full  # pooler/heads: last stage
    return {
        "pipe_size": pipe_size,
        "replicated_bytes": int(replicated),
        "per_chip_bytes": int(per_chip),
        "per_stage_bytes": {k: int(v) for k, v in per_stage.items()},
    }


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _bwd_scale(x, s):
    """Identity forward, ``ct * s`` backward — the one correction the
    gathered-param islands need: stage compute is REPLICATED across the
    ``model`` axis (every TP rank runs the same gathered matmuls), so the
    all-gather transpose (psum_scatter) sums T identical param cotangents;
    scaling the gathered leaves' backward by 1/T restores the exact
    single-path gradient (exact in fp: T is a power of two)."""
    return x


def _bwd_scale_fwd(x, s):
    return x, None


def _bwd_scale_bwd(s, _, ct):
    return (jax.tree_util.tree_map(lambda c: c * s, ct),)


_bwd_scale.defvjp(_bwd_scale_fwd, _bwd_scale_bwd)


def _gather_leaf(x, spec, *, axis_sizes):
    """Reassemble one stage/TP-sharded leaf INSIDE the island with
    explicit tiled all-gathers over each mesh axis its spec names —
    manual collectives only; GSPMD resharding at the shard_map boundary
    is the known-miscompiling path this module exists to avoid. The
    transpose is psum_scatter per axis, so leaf gradients leave the
    island exactly block-sharded to match the stored layout."""
    for i, ax in enumerate(spec):
        if ax is not None and axis_sizes.get(ax, 1) > 1:
            x = jax.lax.all_gather(x, ax, axis=i, tiled=True)
    return x


def _gather_param_tree(t_params, spec_tree, *, axis_sizes):
    return jax.tree_util.tree_map(
        lambda x, s: _gather_leaf(x, s, axis_sizes=axis_sizes),
        t_params, spec_tree,
    )


# -- pipelined encoder forward ----------------------------------------------

def make_pipeline_encoder(model, plan, *, batch_split: int,
                          deterministic: bool,
                          prng_impl: str = "threefry2x32",
                          stage_specs=None):
    """Build ``encode(params, micro_inputs, base_key) -> (seq_out,
    pooled)`` running the encoder trunk on the GPipe schedule.

    ``params`` is the full (replicated) QAModel param tree;
    ``micro_inputs`` the ``[G, B_micro, ...]`` micro-split input planes
    the gradient-accumulation scan already uses (rows sharded over
    ``data`` on dim 1). Outputs are ``[G, B_micro, L, H]`` sequence
    states and ``[G, B_micro, (S,) H]`` pooled vectors — the QA heads
    and the loss run on them exactly as on the sequential path.

    The schedule is an EXPLICIT ``shard_map`` over the ``pipe`` axis
    (MPMD discipline, arxiv 2412.14374): each pipe rank runs only its
    own stage's layers per tick (``lax.switch`` on the rank index), the
    per-tick activation hand-off is a literal ``lax.ppermute`` to the
    next rank, and the collected last-stage outputs come back through
    one masked psum. Nothing is left to the auto-partitioner's choices —
    on the virtual CPU mesh, GSPMD's resharding of in-jit-stacked
    replicated params onto a ``pipe``-sharded layout was observed to
    MISCOMPUTE (see tests/test_parallel_plan.py parity pins), which is
    exactly the class of silent wrongness the explicit formulation
    removes. Rank 0 also evaluates the (cheap) embedding refill every
    tick; other ranks discard it, so its gradient flows only once.

    Dropout keys are pure functions of (base_key, micro index, global
    layer index): deterministic and resume-stable, but a DIFFERENT
    stream than the sequential path's flax module-path folding —
    pipeline trajectories are pinned against single-axis runs with
    dropout off (reduction-order tolerance), matching the DDP precedent
    that never promised cross-topology dropout determinism.

    ``stage_specs`` (a :func:`stage_param_specs` tree for the FULL param
    tree) switches on stage-local storage: the trunk leaves enter the
    island pre-sharded per spec and are reassembled with explicit tiled
    ``all_gather`` — whose transpose (psum_scatter) returns gradients
    exactly block-sharded to the stored layout. When the mesh also has a
    ``model`` axis the stage compute is replicated across TP ranks, so
    every trunk leaf's backward is scaled 1/T (:func:`_bwd_scale`) to
    cancel the replicated-cotangent psum.
    """
    import flax.linen as nn
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..models.encoder import Embeddings, EncoderLayer, _dense
    from .sharding import DATA_AXIS, PIPE_AXIS

    cfg = model.cfg
    mesh = plan.mesh
    K = int(plan.pipe_size)
    G = int(batch_split)
    S = stage_layer_count(cfg.num_layers, K)
    T = G + K - 1
    axis_sizes = {a: int(mesh.shape[a]) for a in mesh.axis_names}
    model_size = int(plan.model_size)
    # a pipe-bearing mesh need not carry a data axis at all (--mesh
    # pipe:2,model:2): batch specs degrade to replicated then
    data_ax = DATA_AXIS if DATA_AXIS in mesh.axis_names else None
    trunk_specs = None if stage_specs is None else stage_specs["transformer"]

    emb_mod = Embeddings(cfg, model.dtype, model.ln_impl)
    layer_cls = EncoderLayer
    if model.remat:
        layer_cls = nn.remat(EncoderLayer, static_argnums=(3,))
    layer_mod = layer_cls(cfg, model.dtype, model.attention_impl,
                          model.mesh, model.ln_impl, quantize=model.quantize)
    pooler_mod = _dense(model.quantize, cfg.hidden_size, name="pooler",
                        dtype=model.dtype)

    def encode(params, micro_inputs, base_key):
        t_params = params["transformer"]
        seg_starts = micro_inputs.get("segment_starts")
        has_seg = micro_inputs.get("segment_ids") is not None
        planes = {
            k: micro_inputs[k]
            for k in ("input_ids", "attention_mask", "token_type_ids",
                      "position_ids", "segment_ids")
            if micro_inputs.get(k) is not None
        }
        if "attention_mask" not in planes:
            planes["attention_mask"] = jnp.ones_like(planes["input_ids"])
        if "token_type_ids" not in planes:
            planes["token_type_ids"] = jnp.zeros_like(planes["input_ids"])
        # keys cross the shard_map boundary as raw uint32 data (extended
        # key dtypes through shard_map are version-fragile)
        kd = jax.random.key_data(base_key)

        def body(t_params, planes, kd):
            if trunk_specs is not None:
                t_params = _gather_param_tree(
                    t_params, trunk_specs, axis_sizes=axis_sizes
                )
            if model_size > 1:
                # stage compute is replicated across TP ranks — cancel
                # the T-fold cotangent psum (see _bwd_scale)
                t_params = jax.tree_util.tree_map(
                    lambda x: _bwd_scale(x, 1.0 / model_size), t_params
                )
            k_idx = jax.lax.axis_index(PIPE_AXIS)
            is_first = k_idx == 0
            base = jax.random.wrap_key_data(kd, impl=prng_impl)
            input_ids = planes["input_ids"]
            mask = planes["attention_mask"]
            ttype = planes["token_type_ids"]
            pos_ids = planes.get("position_ids")
            seg_ids = planes.get("segment_ids")
            B, Lseq = input_ids.shape[1], input_ids.shape[2]

            def micro_key(i):
                # i runs out of [0, G) on warmup/drain lanes — those keys
                # (and the activations they drop) are garbage that never
                # reaches a collected output
                return jax.random.fold_in(base, i)

            def take(x, i, *, keep=False):
                return jax.lax.dynamic_index_in_dim(
                    x, jnp.clip(i, 0, G - 1), 0, keepdims=keep
                )

            def embed_micro(i):
                return emb_mod.apply(
                    {"params": t_params["embeddings"]},
                    take(input_ids, i), take(ttype, i),
                    deterministic=deterministic,
                    position_ids=(
                        None if pos_ids is None else take(pos_ids, i)
                    ),
                    rngs={"dropout": jax.random.fold_in(micro_key(i), 0)},
                )

            def run_stage(kk, h, m, sg, micro_idx):
                # stage kk = contiguous layers [kk*S, (kk+1)*S)
                for s in range(S):
                    li = kk * S + s
                    key_l = jax.random.fold_in(
                        micro_key(micro_idx), 1 + li
                    )
                    h = layer_mod.apply(
                        {"params": t_params[f"layer_{li}"]}, h, m,
                        deterministic, sg if has_seg else None,
                        rngs={"dropout": key_l},
                    )
                return h

            def stage(h, m, sg, micro_idx):
                # each rank executes exactly ONE branch — its own stage
                branches = [
                    functools.partial(run_stage, kk) for kk in range(K)
                ]
                return jax.lax.switch(k_idx, branches, h, m, sg, micro_idx)

            h0 = embed_micro(jnp.int32(0))
            h = jnp.where(is_first, h0, jnp.zeros_like(h0))
            m = jnp.where(is_first, take(mask, jnp.int32(0)),
                          jnp.zeros_like(mask[0]))
            # the segment plane rides the rotation as a dummy when
            # packing is off (one [B, L] int buffer — cheap) so the
            # carry/switch structure is static
            seg_src = seg_ids if has_seg else mask
            sg = jnp.where(is_first, take(seg_src, jnp.int32(0)),
                           jnp.zeros_like(seg_src[0]))
            out0 = jnp.zeros((G, B, Lseq, int(cfg.hidden_size)), h0.dtype)
            perm = [(i, (i + 1) % K) for i in range(K)]

            def tick(carry, t):
                h, m, sg, out = carry
                micro_idx = t - k_idx
                y = stage(h, m, sg, micro_idx)
                # collect the LAST stage's output. Before tick K-1 the
                # write lands (clipped) on slot 0 with warmup garbage —
                # tick K-1 overwrites it with micro-batch 0's true
                # output, and every later slot is written exactly once
                # at its true tick, so no per-tick select is needed
                slot = jnp.clip(t - (K - 1), 0, G - 1)
                out = jax.lax.dynamic_update_slice(
                    out, y[None].astype(out.dtype), (slot, 0, 0, 0)
                )
                # the stage-boundary hand-off: activations (and their
                # mask/segment planes) cross to rank k+1 via collective
                # permute; rank 0 refills from the next micro-batch
                nxt = t + 1
                y_n = jax.lax.ppermute(y, PIPE_AXIS, perm)
                m_n = jax.lax.ppermute(m, PIPE_AXIS, perm)
                sg_n = jax.lax.ppermute(sg, PIPE_AXIS, perm)
                h = jnp.where(is_first, embed_micro(nxt).astype(y_n.dtype),
                              y_n)
                m = jnp.where(is_first, take(mask, nxt), m_n)
                sg = jnp.where(is_first, take(seg_src, nxt), sg_n)
                return (h, m, sg, out), None

            (_, _, _, out), _ = jax.lax.scan(
                tick, (h, m, sg, out0), jnp.arange(T, dtype=jnp.int32)
            )
            # only rank K-1 collected real outputs; the masked psum is
            # the one gather that returns them to every rank
            out = out * (k_idx == K - 1).astype(out.dtype)
            return jax.lax.psum(out, PIPE_AXIS)

        t_in_specs = P() if trunk_specs is None else trunk_specs
        seq_out = shard_map(
            body, mesh,
            in_specs=(t_in_specs, P(None, data_ax, None), P()),
            out_specs=P(None, data_ax, None, None),
            check_rep=False,
        )(t_params, planes, kd)

        # pooled output — the encoder tail (encoder.py): each row's [CLS]
        # (or each packed segment's own [CLS]) through the pooler Dense;
        # plain data-parallel compute outside the pipeline island
        if seg_starts is None:
            pool_src = seq_out[:, :, 0]
        else:
            pool_src = jnp.take_along_axis(
                seq_out, seg_starts[..., None].astype(jnp.int32), axis=2
            )
        pooled = jnp.tanh(
            pooler_mod.apply({"params": t_params["pooler"]}, pool_src)
        )
        return seq_out, pooled

    return encode


def apply_qa_heads(model, params, sequence_output, pooled_output,
                   attention_mask, *, deterministic, dropout_rng,
                   segment_ids=None, segment_starts=None):
    """The QA heads on ONE micro-batch of (pipelined) encoder outputs —
    mirrors the post-trunk body of ``QAModel.__call__`` (span logits with
    pad masking, per-segment confinement when packed, classifier on the
    dropped-out pooled vector, sigmoid regressors). Parameters are the
    same head leaves, so the two paths are interchangeable; parity with
    the sequential forward is pinned in tests/test_parallel_plan.py.
    """
    from ..models.encoder import _dense
    from ..models.qa_model import _MASK_NEG
    import flax.linen as nn

    cfg = model.cfg
    packed = segment_starts is not None

    position_logits = _dense(
        model.quantize, 2, name="position_outputs", dtype=model.dtype
    ).apply({"params": params["position_outputs"]}, sequence_output)
    start_logits = position_logits[..., 0]
    end_logits = position_logits[..., 1]

    pad_penalty = (1 - attention_mask).astype(jnp.float32) * _MASK_NEG
    start_logits = start_logits.astype(jnp.float32) + pad_penalty
    end_logits = end_logits.astype(jnp.float32) + pad_penalty

    if packed:
        S = segment_starts.shape[1]
        seg_eq = (
            segment_ids[:, None, :]
            == (1 + jnp.arange(S, dtype=segment_ids.dtype))[None, :, None]
        )
        seg_penalty = jnp.where(seg_eq, 0.0, jnp.float32(_MASK_NEG))
        start_logits = start_logits[:, None, :] + seg_penalty
        end_logits = end_logits[:, None, :] + seg_penalty

    cls_hidden = nn.Dropout(cfg.hidden_dropout_prob).apply(
        {}, pooled_output, deterministic=deterministic,
        rngs={"dropout": dropout_rng},
    )
    classifier_logits = _dense(
        model.quantize, cfg.num_labels, name="classifier", dtype=model.dtype
    ).apply({"params": params["classifier"]}, cls_hidden)

    reg_start = nn.sigmoid(
        _dense(model.quantize, 1, name="reg_start", dtype=model.dtype)
        .apply({"params": params["reg_start"]}, pooled_output)
    )[..., 0]
    reg_end = nn.sigmoid(
        _dense(model.quantize, 1, name="reg_end", dtype=model.dtype)
        .apply({"params": params["reg_end"]}, pooled_output)
    )[..., 0]

    return {
        "start_class": start_logits,
        "end_class": end_logits,
        "start_reg": reg_start.astype(jnp.float32),
        "end_reg": reg_end.astype(jnp.float32),
        "cls": classifier_logits.astype(jnp.float32),
    }


# -- 1F1B schedule ------------------------------------------------------------

def make_pipeline_train_step(model, loss, plan, *, batch_split: int,
                             prng_impl: str = "threefry2x32",
                             stage_specs=None):
    """Build ``run(params, micro_inputs, micro_labels, base_key, scale)
    -> (grads, values)``: the 1F1B tick program as ONE manual-VJP
    ``shard_map`` island (forward, heads, loss and backward all inside —
    same ppermute discipline as the GPipe island, no GSPMD boundary
    resharding anywhere).

    Schedule: at tick t stage k runs the forward of micro ``f = t - k``
    AND the backward of micro ``b = t - 2(K-1) + k`` (one-forward-one-
    backward; on the last stage b == f, so it fuses forward + heads +
    loss + backward in one tick). The program runs ``m + 2(K-1)`` ticks
    and keeps only ``W = min(m, 2K-1)`` stage inputs resident — the
    activation cap GPipe's hold-all-m schedule lacks — recomputing each
    stage forward at backward time from its saved input (bitwise
    identical: same weights, same dropout keys).

    Correctness accounting (each proved against the sequential scan):

    - backward = ``jax.vjp`` of the stage recompute seeded with the
      cotangent ppermuted back from stage k+1 (the mirrored pipeline,
      written out by hand instead of autodiff's transpose);
    - the loss is computed on FULL batch rows — local head outputs and
      labels are all-gathered over ``data`` (tiled, so row order matches
      the global batch) — because the losses' normalizers
      (valid-row counts, losses.py) are data-dependent: a local-shard
      loss would change the arithmetic. The vjp seed is ``scale / D``
      since the all-gather transpose psum-scatters D identical
      cotangents back;
    - gradients accumulate masked (``where`` selects, so warmup/drain
      garbage never taints the sum), are psum'd over ``pipe`` (stages
      own disjoint layers) and ``data`` (ranks own disjoint rows) but
      NOT ``model`` (TP ranks run identical gathered compute — summing
      would double-count; each keeps its own block), then each rank
      slices its own stage/TP block so grads leave the island exactly
      in the stored stage-local layout.
    """
    import flax.linen as nn
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..models.encoder import Embeddings, EncoderLayer, _dense
    from .sharding import DATA_AXIS, PIPE_AXIS

    cfg = model.cfg
    mesh = plan.mesh
    K = int(plan.pipe_size)
    G = int(batch_split)
    S = stage_layer_count(cfg.num_layers, K)
    W = min(G, 2 * K - 1)
    T = G + 2 * (K - 1)
    num_layers = int(cfg.num_layers)
    axis_sizes = {a: int(mesh.shape[a]) for a in mesh.axis_names}
    model_size = int(plan.model_size)
    data_ax = DATA_AXIS if DATA_AXIS in mesh.axis_names else None
    data_size = axis_sizes.get(DATA_AXIS, 1)
    trunk_specs = None if stage_specs is None else stage_specs["transformer"]

    emb_mod = Embeddings(cfg, model.dtype, model.ln_impl)
    layer_cls = EncoderLayer
    if model.remat:
        layer_cls = nn.remat(EncoderLayer, static_argnums=(3,))
    layer_mod = layer_cls(cfg, model.dtype, model.attention_impl,
                          model.mesh, model.ln_impl, quantize=model.quantize)
    pooler_mod = _dense(model.quantize, cfg.hidden_size, name="pooler",
                        dtype=model.dtype)

    def run(params, micro_inputs, micro_labels, base_key, scale):
        seg_starts = micro_inputs.get("segment_starts")
        has_seg = micro_inputs.get("segment_ids") is not None
        planes = {
            k: micro_inputs[k]
            for k in ("input_ids", "attention_mask", "token_type_ids",
                      "position_ids", "segment_ids", "segment_starts")
            if micro_inputs.get(k) is not None
        }
        if "attention_mask" not in planes:
            planes["attention_mask"] = jnp.ones_like(planes["input_ids"])
        if "token_type_ids" not in planes:
            planes["token_type_ids"] = jnp.zeros_like(planes["input_ids"])
        kd = jax.random.key_data(base_key)

        def body(params, planes, labels, kd, scale):
            t_params = params["transformer"]
            if trunk_specs is not None:
                t_params = _gather_param_tree(
                    t_params, trunk_specs, axis_sizes=axis_sizes
                )
            head_params = {
                "pooler": t_params["pooler"],
                "position_outputs": params["position_outputs"],
                "classifier": params["classifier"],
                "reg_start": params["reg_start"],
                "reg_end": params["reg_end"],
            }
            k_idx = jax.lax.axis_index(PIPE_AXIS)
            is_first = k_idx == 0
            is_last = k_idx == K - 1
            # Dropout keys in this island are pipe-rank-VARYING by
            # construction (micro index f = t - k), which rules out the
            # rbg impl: its rng_bit_generator lowering demands a
            # rank-replicated key, so XLA rewrites a varying key into a
            # select + u64 all-reduce broadcast — placed INSIDE the
            # stage-divergent switch branches, where stage 0 and stage 1
            # rendezvous on different channels and deadlock (and every
            # rank would draw identical bits besides). Threefry lowers to
            # partitionable per-element arithmetic, so the island always
            # derives threefry keys, seeding them from the caller's raw
            # key words whatever impl those came from. (The GPipe island
            # keeps the caller's impl: its micro index is the rank-uniform
            # scan counter, so its keys stay replicated and rbg is safe.)
            if prng_impl == "threefry2x32":
                base = jax.random.wrap_key_data(kd, impl=prng_impl)
            else:
                base = jax.random.key(0, impl="threefry2x32")
                for w in kd.reshape(-1):
                    base = jax.random.fold_in(base, w)
            input_ids = planes["input_ids"]
            mask = planes["attention_mask"]
            ttype = planes["token_type_ids"]
            pos_ids = planes.get("position_ids")
            seg_ids = planes.get("segment_ids")
            ss = planes.get("segment_starts")
            B, Lseq = input_ids.shape[1], input_ids.shape[2]

            def micro_key(i):
                return jax.random.fold_in(base, i)

            def take(x, i, *, keep=False):
                return jax.lax.dynamic_index_in_dim(
                    x, jnp.clip(i, 0, G - 1), 0, keepdims=keep
                )

            def embed_with(e_params, i):
                return emb_mod.apply(
                    {"params": e_params},
                    take(input_ids, i), take(ttype, i),
                    deterministic=False,
                    position_ids=(
                        None if pos_ids is None else take(pos_ids, i)
                    ),
                    rngs={"dropout": jax.random.fold_in(micro_key(i), 0)},
                )

            def run_stage(kk, tp, h, m, sg, micro_idx):
                for s in range(S):
                    li = kk * S + s
                    key_l = jax.random.fold_in(micro_key(micro_idx), 1 + li)
                    h = layer_mod.apply(
                        {"params": tp[f"layer_{li}"]}, h, m,
                        False, sg if has_seg else None,
                        rngs={"dropout": key_l},
                    )
                return h

            def stage(tp, h, m, sg, micro_idx):
                branches = [
                    functools.partial(run_stage, kk) for kk in range(K)
                ]
                return jax.lax.switch(
                    k_idx, branches, tp, h, m, sg, micro_idx
                )

            def head_loss(hp, y, micro_idx):
                # heads + loss for ONE micro-batch, on FULL batch rows
                # (see docstring: the loss normalizers are data-dependent)
                if ss is None:
                    src = y[:, 0]
                    ss_i = None
                else:
                    ss_i = take(ss, micro_idx)
                    src = jnp.take_along_axis(
                        y, ss_i[..., None].astype(jnp.int32), axis=1
                    )
                pooled = jnp.tanh(
                    pooler_mod.apply({"params": hp["pooler"]}, src)
                )
                preds = apply_qa_heads(
                    model, hp, y, pooled, take(mask, micro_idx),
                    deterministic=False,
                    dropout_rng=jax.random.fold_in(
                        micro_key(micro_idx), 1 + num_layers
                    ),
                    segment_ids=(
                        take(seg_ids, micro_idx) if has_seg else None
                    ),
                    segment_starts=ss_i,
                )
                lab = jax.tree_util.tree_map(
                    lambda x: take(x, micro_idx), labels
                )
                if data_ax is not None and data_size > 1:
                    preds = jax.tree_util.tree_map(
                        lambda x: jax.lax.all_gather(
                            x, data_ax, axis=0, tiled=True
                        ), preds,
                    )
                    lab = jax.tree_util.tree_map(
                        lambda x: jax.lax.all_gather(
                            x, data_ax, axis=0, tiled=True
                        ), lab,
                    )
                total_i, values_i = loss(preds, lab)
                return total_i, values_i

            def masked_add(acc, contrib, valid):
                return jax.tree_util.tree_map(
                    lambda a, g: a + jnp.where(
                        valid, g, jnp.zeros_like(g)
                    ).astype(a.dtype),
                    acc, contrib,
                )

            h0 = embed_with(t_params["embeddings"], jnp.int32(0))
            h_init = jnp.where(is_first, h0, jnp.zeros_like(h0))
            zeros_f32 = functools.partial(
                jax.tree_util.tree_map,
                lambda x: jnp.zeros(jnp.shape(x), jnp.float32),
            )
            carry0 = (
                h_init,
                jnp.zeros_like(h0),                       # g_ct
                jnp.zeros((W,) + h0.shape, h0.dtype),     # in_buf
                zeros_f32(t_params),                      # acc_stage
                zeros_f32(t_params["embeddings"]),        # acc_emb
                zeros_f32(head_params),                   # acc_head
                zeros_f32(loss.value_structure()),        # v_acc
            )
            perm_fwd = [(i, (i + 1) % K) for i in range(K)]
            perm_bwd = [(i, (i - 1) % K) for i in range(K)]

            def tick(carry, t):
                h, g_ct, in_buf, acc_stage, acc_emb, acc_head, v_acc = carry
                f = t - k_idx
                b = t - 2 * (K - 1) + k_idx
                f_valid = (f >= 0) & (f < G)
                b_valid = (b >= 0) & (b < G)
                fc = jnp.clip(f, 0, G - 1)
                bc = jnp.clip(b, 0, G - 1)

                # -- forward unit: micro f through this rank's stage
                m_f = take(mask, fc)
                seg_src = seg_ids if has_seg else mask
                sg_f = take(seg_src, fc)
                y = stage(t_params, h, m_f, sg_f, fc)
                # save the stage INPUT for recompute at backward time;
                # masked write so warmup/drain lanes never clobber a
                # live slot (W >= the in-flight window, so micro f-W is
                # fully drained before its slot is reused)
                slot_f = jnp.mod(fc, W)
                cur = jax.lax.dynamic_index_in_dim(
                    in_buf, slot_f, 0, keepdims=False
                )
                in_buf = jax.lax.dynamic_update_slice(
                    in_buf,
                    jnp.where(f_valid, h, cur)[None],
                    (slot_f,) + (0,) * h.ndim,
                )

                # -- heads + loss (every rank computes it on its y so the
                # collectives inside stay uniform; only the last stage's
                # result is real — everything else is masked out)
                (_, head_vjp, values_i) = jax.vjp(
                    lambda hp, yy: head_loss(hp, yy, fc),
                    head_params, y, has_aux=True,
                )
                d_hp, d_y = head_vjp(
                    (scale / data_size).astype(jnp.float32)
                )

                # -- backward unit: recompute micro b's stage forward
                # from the saved input, transpose with jax.vjp
                h_saved = jax.lax.dynamic_index_in_dim(
                    in_buf, jnp.mod(bc, W), 0, keepdims=False
                )
                m_b = take(mask, bc)
                sg_b = take(seg_src, bc)
                _, stage_vjp = jax.vjp(
                    lambda tp, hh: stage(tp, hh, m_b, sg_b, bc),
                    t_params, h_saved,
                )
                ct_in = jnp.where(is_last, d_y, g_ct).astype(h.dtype)
                d_tp, d_h = stage_vjp(ct_in)
                # rank 0's stage input was the embedding output: push the
                # incoming cotangent through the embed recompute
                _, emb_vjp = jax.vjp(
                    lambda ep: embed_with(ep, bc), t_params["embeddings"]
                )
                (d_emb,) = emb_vjp(d_h.astype(h0.dtype))

                acc_stage = masked_add(acc_stage, d_tp, b_valid)
                acc_emb = masked_add(acc_emb, d_emb, b_valid & is_first)
                acc_head = masked_add(acc_head, d_hp, f_valid & is_last)
                v_acc = masked_add(v_acc, values_i, f_valid & is_last)

                # -- hand-offs: activations forward, cotangents backward
                y_n = jax.lax.ppermute(y, PIPE_AXIS, perm_fwd)
                g_ct = jax.lax.ppermute(d_h, PIPE_AXIS, perm_bwd)
                h = jnp.where(
                    is_first,
                    embed_with(t_params["embeddings"], t + 1).astype(
                        y_n.dtype
                    ),
                    y_n,
                )
                return (h, g_ct, in_buf, acc_stage, acc_emb, acc_head,
                        v_acc), None

            (_, _, _, acc_stage, acc_emb, acc_head, v_acc), _ = (
                jax.lax.scan(
                    tick, carry0, jnp.arange(T, dtype=jnp.int32)
                )
            )

            # stages own disjoint layers, data ranks disjoint rows; model
            # ranks ran IDENTICAL compute — no psum there (see docstring)
            grad_axes = tuple(
                a for a in (PIPE_AXIS, data_ax) if a is not None
            )
            acc_stage = jax.lax.psum(acc_stage, grad_axes)
            acc_emb = jax.lax.psum(acc_emb, grad_axes)
            acc_head = jax.lax.psum(acc_head, grad_axes)
            values = jax.lax.psum(v_acc, PIPE_AXIS)

            g_trans = dict(acc_stage)
            g_trans["embeddings"] = acc_emb
            g_trans["pooler"] = acc_head["pooler"]
            grads = {
                "transformer": g_trans,
                "position_outputs": acc_head["position_outputs"],
                "classifier": acc_head["classifier"],
                "reg_start": acc_head["reg_start"],
                "reg_end": acc_head["reg_end"],
            }
            if stage_specs is not None:
                def slice_own(g, spec):
                    for i, ax in enumerate(spec):
                        if ax is not None and axis_sizes.get(ax, 1) > 1:
                            size = g.shape[i] // axis_sizes[ax]
                            g = jax.lax.dynamic_slice_in_dim(
                                g, jax.lax.axis_index(ax) * size, size,
                                axis=i,
                            )
                    return g

                grads = jax.tree_util.tree_map(
                    slice_own, grads, stage_specs
                )
            return grads, values

        p_in_specs = P() if stage_specs is None else stage_specs
        g_out_specs = P() if stage_specs is None else stage_specs
        grads, values = shard_map(
            body, mesh,
            in_specs=(p_in_specs, P(None, data_ax, None),
                      P(None, data_ax), P(), P()),
            out_specs=(g_out_specs, P()),
            check_rep=False,
        )(params, planes, micro_labels, kd, jnp.asarray(scale, jnp.float32))
        return grads, values

    return run
