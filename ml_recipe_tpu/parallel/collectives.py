"""Collective primitives.

The reference leaned on NCCL through DDP: implicit gradient all-reduce in
``loss.backward()`` (trainer.py:136-142), barriers, and SyncBN statistics
sync (trainer.py:89-95). Here collectives are explicit XLA ops used inside
``shard_map``/``pjit``-traced functions; XLA lowers them onto ICI/DCN.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def pmean(tree, axis_name: str):
    """Mean-reduce a pytree over a mesh axis — the DDP gradient-averaging
    contract (SURVEY.md §7 hard part (e)): DDP averages grads over the world,
    so psum/axis_size keeps the reference's LR advice valid."""
    return jax.tree_util.tree_map(lambda x: lax.pmean(x, axis_name), tree)


def psum_scalar(value, axis_name: str):
    return lax.psum(value, axis_name)


def cross_replica_mean(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Cross-replica moment sync — SyncBN parity (trainer.py:89-95). BERT has
    LayerNorm (no cross-sample stats), so this is exposed as a utility for
    models that do carry BatchNorm-style statistics."""
    return lax.pmean(x, axis_name)


def all_gather(x: jnp.ndarray, axis_name: str, *, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)
