"""Collective primitives.

The reference leaned on NCCL through DDP: implicit gradient all-reduce in
``loss.backward()`` (trainer.py:136-142), barriers, and SyncBN statistics
sync (trainer.py:89-95). Here collectives are explicit XLA ops used inside
``shard_map``/``pjit``-traced functions; XLA lowers them onto ICI/DCN.
"""

from __future__ import annotations

from typing import List, NamedTuple, Sequence

import jax
import jax.numpy as jnp
from jax import lax


def pmean(tree, axis_name: str):
    """Mean-reduce a pytree over a mesh axis — the DDP gradient-averaging
    contract (SURVEY.md §7 hard part (e)): DDP averages grads over the world,
    so psum/axis_size keeps the reference's LR advice valid."""
    return jax.tree_util.tree_map(lambda x: lax.pmean(x, axis_name), tree)


def psum_scalar(value, axis_name: str):
    return lax.psum(value, axis_name)


def cross_replica_mean(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Cross-replica moment sync — SyncBN parity (trainer.py:89-95). BERT has
    LayerNorm (no cross-sample stats), so this is exposed as a utility for
    models that do carry BatchNorm-style statistics."""
    return lax.pmean(x, axis_name)


def all_gather(x: jnp.ndarray, axis_name: str, *, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


class GradBucket(NamedTuple):
    """One contiguous run of flattened-tree leaves whose gradients travel
    together: ``lo``/``hi`` index the leaf list (``leaves[lo:hi]``),
    ``size`` is the total element count of the bucket's accumulation
    vector, ``nbytes`` its f32 footprint."""

    lo: int
    hi: int
    size: int
    nbytes: int


def plan_grad_buckets(
    sizes: Sequence[int], *, bucket_bytes: int, itemsize: int = 4
) -> List[GradBucket]:
    """Partition per-leaf element counts into size-targeted CONTIGUOUS
    buckets (the DDP overlap discipline, arxiv 2004.13336): leaves are
    walked in tree order and a bucket closes once it reaches
    ``bucket_bytes`` of accumulation-dtype payload, so a single oversized
    leaf gets a bucket of its own and small leaves coalesce.

    Contiguity is load-bearing twice over: the concatenation of the bucket
    vectors reproduces the one monolithic flat gradient vector element for
    element (which is what lets ``--zero1_overlap bucketed`` keep the
    global-norm clip — computed over that concatenation — the same
    arithmetic as the unbucketed step; the two programs still PARTITION
    differently, so trajectories agree to GSPMD reduction-order tolerance,
    the same bound the zero1-vs-replicated equivalence pins use), and each
    bucket's reduce-scatter depends only on its own carry, so XLA can
    schedule the per-bucket exchanges independently instead of fusing one
    tail collective behind the full flat vector.
    """
    bucket_bytes = max(1, int(bucket_bytes))
    buckets: List[GradBucket] = []
    lo = 0
    acc = 0

    def close(hi: int, nbytes: int) -> None:
        buckets.append(
            GradBucket(lo, hi, sum(int(s) for s in sizes[lo:hi]), nbytes)
        )

    for i, size in enumerate(sizes):
        nbytes = int(size) * itemsize
        if nbytes >= bucket_bytes and acc > 0:
            # an oversized leaf must get a bucket of its OWN: close the
            # running bucket first instead of swallowing the small leaves
            # into one giant (less overlappable) exchange
            close(i, acc)
            lo, acc = i, 0
        acc += nbytes
        if acc >= bucket_bytes:
            close(i + 1, acc)
            lo, acc = i + 1, 0
    if lo < len(sizes):
        close(len(sizes), acc)
    return buckets
