"""Device mesh construction — the topology half of the declarative
:class:`~.plan.ParallelPlan`.

The topology is a named `jax.sharding.Mesh` with up to four first-class
axes, declared ONCE via ``--mesh`` and consumed everywhere through the
ParallelPlan (trainer, predictor, serving engine, ZeRO-1 planner, HBM
pre-flight, checkpoint manifests all *derive* their shardings from it —
no per-feature rewiring):

- ``pipe``  — pipeline parallelism: contiguous encoder-layer stages on a
  GPipe micro-batch schedule (parallel/pipeline.py)
- ``data``  — data parallelism (batch rows; gradients reduce over this
  axis, ZeRO-1 shards optimizer state over it)
- ``seq``   — sequence/context parallelism for long inputs (ring attention)
- ``model`` — tensor parallelism over attention heads / MLP width

Axis sizes come from the ``--mesh`` flag ("data:4,pipe:2"); by default all
visible devices form one data axis. Works identically on real TPU meshes and
the virtual 8-CPU-device test mesh.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

logger = logging.getLogger(__name__)

# pipe outermost (stages talk point-to-point, the cheapest links can carry
# them), data next, model innermost so model groups land on neighbouring
# devices — ICI-friendly (TorchTitan's pp > dp > tp ordering).
AXIS_ORDER = ("pipe", "data", "seq", "model")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    axes: Dict[str, int]

    @classmethod
    def from_string(cls, spec: Optional[str], n_devices: Optional[int] = None) -> "MeshSpec":
        from ..config.parser import parse_mesh_spec

        axes = parse_mesh_spec(spec)
        if not axes:
            axes = {"data": n_devices if n_devices is not None else len(jax.devices())}
        return cls(axes=axes)

    @property
    def size(self) -> int:
        return math.prod(self.axes.values())

    def ordered(self) -> Dict[str, int]:
        """Axes in canonical order (data outermost, model innermost so model
        groups land on neighbouring devices — ICI-friendly)."""
        out = {name: self.axes[name] for name in AXIS_ORDER if name in self.axes}
        for name, size in self.axes.items():  # preserve any custom axes
            if name not in out:
                out[name] = size
        return out


class ElasticMeshError(ValueError):
    """A requested mesh cannot be re-derived over the live device set —
    a STRUCTURAL axis (pipe/seq/model) would have to change size."""


def elastic_axes(
    axes: Dict[str, int], n_devices: int, *, min_data: int = 1
) -> Dict[str, int]:
    """Shrink a requested axes dict onto ``n_devices`` live devices.

    Only the DATA axis shrinks: pipeline stages hold disjoint layer
    shards, and seq/model groups hold disjoint tensor shards — changing
    any of those sizes changes what each device OWNS, which the
    crop/zero-fill checkpoint reconciliation cannot express. The data
    axis, by contrast, only replicates: narrowing it keeps every
    parameter whole and reshapes ZeRO-1 optimizer shards, which
    ``reconcile_state_shapes`` handles. Refusals are loud and specific —
    an elastic restart that silently trained a different model shape
    would be far worse than an abort.
    """
    requested = dict(axes)
    total = math.prod(requested.values())
    if total <= n_devices:
        return requested
    structural = {k: v for k, v in requested.items() if k != "data"}
    fixed = math.prod(structural.values()) if structural else 1
    if fixed > n_devices:
        raise ElasticMeshError(
            f"cannot shrink mesh {requested} onto {n_devices} device(s): "
            f"the structural axes {structural} alone need {fixed} devices. "
            f"Only the data axis shrinks elastically — pipe/seq/model "
            f"change what each device OWNS (layer/tensor shards), which "
            f"checkpoint reconciliation cannot re-derive. Relaunch with a "
            f"smaller --mesh or restore the lost hosts."
        )
    new_data = n_devices // fixed
    if new_data < max(1, int(min_data)):
        raise ElasticMeshError(
            f"cannot shrink mesh {requested} onto {n_devices} device(s): "
            f"the data axis would narrow to {new_data}, below the floor of "
            f"{min_data} — training that narrow is degenerate (see "
            f"--min_world)."
        )
    out = {k: (new_data if k == "data" else v) for k, v in requested.items()}
    if "data" not in out:
        # a pure-structural request that happens to fit was returned above;
        # here the request had no data axis AND does not fit — unreachable
        # unless fixed > n_devices, already raised. Keep the guard anyway.
        raise ElasticMeshError(
            f"mesh {requested} has no data axis to shrink onto "
            f"{n_devices} device(s)."
        )
    logger.warning(
        "ELASTIC: shrinking mesh %s -> %s over %d live device(s) "
        "(data axis %d -> %d; structural axes unchanged).",
        requested, out, n_devices, requested.get("data", 1), new_data,
    )
    return out


def build_mesh(
    spec: Optional[str] = None,
    *,
    devices: Optional[Sequence] = None,
    axes: Optional[Dict[str, int]] = None,
) -> Mesh:
    """Build a Mesh from a spec string / axes dict over the given devices."""
    devices = list(devices if devices is not None else jax.devices())

    if axes is not None:
        mesh_spec = MeshSpec(axes=dict(axes))
    else:
        mesh_spec = MeshSpec.from_string(spec, n_devices=len(devices))

    ordered = mesh_spec.ordered()
    if mesh_spec.size > len(devices):
        raise ValueError(
            f"Mesh axes {ordered} require {mesh_spec.size} devices, "
            f"but {len(devices)} are visible."
        )
    if mesh_spec.size < len(devices):
        # a loud warning, not an info line: stranded accelerators are paid
        # for and idle — the count also surfaces as `mesh_unused_devices`
        # in the HBM pre-flight report and the bench train JSON
        logger.warning(
            "Mesh %s uses only the first %d of %d visible devices — "
            "%d device(s) are STRANDED (idle but allocated). Widen an "
            "axis (--mesh) to cover them.",
            ordered, mesh_spec.size, len(devices),
            len(devices) - mesh_spec.size,
        )
        devices = devices[: mesh_spec.size]

    device_array = np.asarray(devices).reshape(tuple(ordered.values()))
    mesh = Mesh(device_array, axis_names=tuple(ordered.keys()))
    logger.info(f"Built device mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}.")
    return mesh


def local_device_count(mesh: Mesh) -> int:
    return len([d for d in mesh.devices.flat if d.process_index == jax.process_index()])


def unused_device_count(mesh: Mesh) -> int:
    """Visible devices the mesh leaves idle (``build_mesh`` warns about
    them; pre-flight reports and bench JSON surface this count so stranded
    chips are visible, not logged-and-lost)."""
    return max(0, len(jax.devices()) - int(mesh.devices.size))
