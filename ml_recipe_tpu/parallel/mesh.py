"""Device mesh construction.

The reference's only topology concept is "world size × GPUs per node" for DDP
(train.py:133-136). Here the topology is a named `jax.sharding.Mesh` with up
to three axes:

- ``data``  — data parallelism (replaces DDP; gradients psum over this axis)
- ``model`` — tensor parallelism over attention heads / MLP width (no
  reference counterpart; SURVEY.md §2.3 stretch)
- ``seq``   — sequence/context parallelism for long inputs (ring attention)

Axis sizes come from the ``--mesh`` flag ("data:4,model:2"); by default all
visible devices form one data axis. Works identically on real TPU meshes and
the virtual 8-CPU-device test mesh.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

logger = logging.getLogger(__name__)

AXIS_ORDER = ("data", "seq", "model")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    axes: Dict[str, int]

    @classmethod
    def from_string(cls, spec: Optional[str], n_devices: Optional[int] = None) -> "MeshSpec":
        from ..config.parser import parse_mesh_spec

        axes = parse_mesh_spec(spec)
        if not axes:
            axes = {"data": n_devices if n_devices is not None else len(jax.devices())}
        return cls(axes=axes)

    @property
    def size(self) -> int:
        return math.prod(self.axes.values())

    def ordered(self) -> Dict[str, int]:
        """Axes in canonical order (data outermost, model innermost so model
        groups land on neighbouring devices — ICI-friendly)."""
        out = {name: self.axes[name] for name in AXIS_ORDER if name in self.axes}
        for name, size in self.axes.items():  # preserve any custom axes
            if name not in out:
                out[name] = size
        return out


def build_mesh(
    spec: Optional[str] = None,
    *,
    devices: Optional[Sequence] = None,
    axes: Optional[Dict[str, int]] = None,
) -> Mesh:
    """Build a Mesh from a spec string / axes dict over the given devices."""
    devices = list(devices if devices is not None else jax.devices())

    if axes is not None:
        mesh_spec = MeshSpec(axes=dict(axes))
    else:
        mesh_spec = MeshSpec.from_string(spec, n_devices=len(devices))

    ordered = mesh_spec.ordered()
    if mesh_spec.size > len(devices):
        raise ValueError(
            f"Mesh axes {ordered} require {mesh_spec.size} devices, "
            f"but {len(devices)} are visible."
        )
    if mesh_spec.size < len(devices):
        logger.info(
            f"Mesh uses the first {mesh_spec.size} of {len(devices)} visible devices."
        )
        devices = devices[: mesh_spec.size]

    device_array = np.asarray(devices).reshape(tuple(ordered.values()))
    mesh = Mesh(device_array, axis_names=tuple(ordered.keys()))
    logger.info(f"Built device mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}.")
    return mesh


def local_device_count(mesh: Mesh) -> int:
    return len([d for d in mesh.devices.flat if d.process_index == jax.process_index()])
