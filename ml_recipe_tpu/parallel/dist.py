"""Multi-host bootstrap and synchronization.

Parity target: the reference's NCCL process-group init + TCP rendezvous
(train.py:27-28, ``tcp://MASTER_IP:9080`` parser.py:166-167) and its explicit
barriers (train.py:55, trainer.py:319). TPU-native replacement:
``jax.distributed.initialize`` (one process per HOST, not per device) driven
by the same env-var contract the platform launcher exports
(MASTER_IP/MASTER_PORT/WORLD_SIZE/LOCAL_RANK, reference .neuro/live.yml:126-132
and scripts/worker.sh), and barriers via a tiny all-reduce across all devices.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

from ..resilience.coordination import ELASTIC_WORLD_ENV
from ..resilience.faults import fire as _fault
from ..resilience.watchdog import current as _current_watchdog
from ..resilience.watchdog import watched as _watched

logger = logging.getLogger(__name__)

_initialized = False


def elastic_world_override() -> Optional[tuple]:
    """``(world_size, process_id)`` from :data:`ELASTIC_WORLD_ENV`
    (``"<size>:<rank>"``), set per attempt by the elastic supervisor so a
    restarted child joins the CURRENT live world instead of the
    params-declared one. None when unset; malformed values are a hard
    error — a child silently joining the wrong world is the one thing an
    elastic restart must never do."""
    raw = os.environ.get(ELASTIC_WORLD_ENV)
    if not raw:
        return None
    try:
        size_s, rank_s = raw.split(":")
        size, rank = int(size_s), int(rank_s)
    except ValueError:
        raise ValueError(
            f"malformed {ELASTIC_WORLD_ENV}={raw!r}; expected "
            f"'<world_size>:<process_id>' (e.g. '2:0')."
        ) from None
    if size < 1 or not (0 <= rank < size):
        raise ValueError(
            f"inconsistent {ELASTIC_WORLD_ENV}={raw!r}: need "
            f"world_size >= 1 and 0 <= process_id < world_size."
        )
    return size, rank


def _strip_scheme(address: str) -> str:
    for scheme in ("tcp://", "grpc://"):
        if address.startswith(scheme):
            return address[len(scheme):]
    return address


def initialize_distributed(
    *,
    coordinator_address: Optional[str] = None,
    num_processes: int = 1,
    process_id: int = 0,
) -> None:
    """Join the multi-host world. No-op for single-process runs (the
    reference likewise skips init_process_group when world_size == 1,
    train.py:135,141-148)."""
    global _initialized
    if num_processes <= 1 or _initialized:
        return

    address = _strip_scheme(coordinator_address or "127.0.0.1:9080")
    logger.warning(
        "Waiting for every worker to reach the coordinator; startup may be slow."
    )
    # drill site: a rendezvous that never completes (one host missing) is
    # the canonical multi-node startup failure — injectable as stall/raise.
    # Fired INSIDE the watch frame so an injected stall exercises the same
    # watchdog path the real hang would take. The frame gets 8x the
    # step-scale timeout (like checkpoint saves): a pod cold start
    # legitimately waits minutes for the slowest host's container, and a
    # slow-but-healthy startup must not be escalated into a crash-loop.
    _wd = _current_watchdog()
    with _watched(
        f"distributed rendezvous {address}",
        _wd.timeout * 8 if _wd is not None else None,
    ):
        _fault("dist.rendezvous")
        jax.distributed.initialize(
            coordinator_address=address,
            num_processes=num_processes,
            process_id=process_id,
        )
    _initialized = True
    logger.info(
        f"Joined distributed world: process {process_id}/{num_processes}, "
        f"coordinator {address}, {jax.device_count()} global devices."
    )


def initialize_from_params(params) -> None:
    """Bootstrap from the trainer flags (reference names preserved).

    The elastic supervisor's per-attempt world override wins over the
    params-declared topology: after a host loss the survivors re-form a
    SMALLER world, and the flags still describe the original one."""
    override = elastic_world_override()
    if override is not None:
        size, rank = override
        logger.warning(
            f"ELASTIC: world override {ELASTIC_WORLD_ENV} -> joining as "
            f"process {rank}/{size} (params declared "
            f"{getattr(params, 'dist_world_size', 1)})."
        )
        initialize_distributed(
            coordinator_address=getattr(params, "dist_init_method", None),
            num_processes=size,
            process_id=rank,
        )
        return
    local_rank = getattr(params, "local_rank", -1)
    world_size = getattr(params, "dist_world_size", 1)
    if world_size > 1 and local_rank < 0:
        raise AttributeError("Specify local rank.")
    initialize_distributed(
        coordinator_address=getattr(params, "dist_init_method", None),
        num_processes=world_size,
        process_id=max(local_rank, 0),
    )


def initialize_from_env() -> None:
    """Bootstrap from the platform launcher env contract
    (MASTER_IP/MASTER_PORT/WORLD_SIZE/LOCAL_RANK)."""
    world_size = int(os.environ.get("WORLD_SIZE", "1"))
    if world_size <= 1:
        return
    master_ip = os.environ.get("MASTER_IP", "127.0.0.1")
    master_port = os.environ.get("MASTER_PORT", "9080")
    local_rank = int(os.environ.get("LOCAL_RANK", "0"))
    initialize_distributed(
        coordinator_address=f"{master_ip}:{master_port}",
        num_processes=world_size,
        process_id=local_rank,
    )


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_primary() -> bool:
    """Process 0 — the reference's ``local_rank in [-1, 0]`` gate."""
    return jax.process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Block until every process reaches this point (train.py:55 parity).

    The fault site fires BEFORE the single-process early return so barrier
    stall/kill drills work under ``JAX_PLATFORMS=cpu`` test worlds too, and
    INSIDE the watch frame so an injected stall takes the same watchdog
    path a peer-never-arrives hang would: stack dump + abort + supervised
    restart instead of an indefinitely wedged pod.
    """
    with _watched(f"barrier:{name}"):
        _fault("dist.barrier")
        if jax.process_count() == 1:
            return
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


# -- native host-coordination helper (native/coord) ---------------------------
#
# Replaces what the reference's shell launch protocol did around the NCCL
# rendezvous: workers polling "is the master up yet" and the launcher's
# all-hosts-ready barrier (run_distributed_on_platform.sh:6-15, worker.sh:1-5).

_qacoord = None


def _load_qacoord():
    global _qacoord
    if _qacoord is not None:
        return _qacoord

    import ctypes

    from ml_recipe_tpu.utils.nativelib import load_native_lib

    lib = load_native_lib("libqacoord.so")
    if lib is None:
        return None
    lib.qacoord_wait.restype = ctypes.c_int
    lib.qacoord_wait.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    lib.qacoord_serve.restype = ctypes.c_int
    lib.qacoord_serve.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int]
    _qacoord = lib
    return lib


def wait_for_coordinator(
    host: str, port: int, *, rank: int = 0, timeout_s: int = 300
) -> bool:
    """Block until the coordinator answers this worker's readiness handshake
    ('w' + 4-byte rank — identity keeps retried/stale connections from being
    double-counted). Native (C++) when built; pure-Python otherwise."""
    lib = _load_qacoord()
    if lib is not None:
        return (
            lib.qacoord_wait(host.encode(), int(port), int(timeout_s), int(rank))
            == 0
        )

    import socket
    import struct
    import time as _time

    deadline = _time.monotonic() + timeout_s
    while _time.monotonic() < deadline:
        try:
            with socket.create_connection((host, port), timeout=2) as s:
                s.sendall(b"w" + struct.pack("!I", rank))
                if s.recv(1) == b"g":
                    return True
        except OSError:
            pass
        _time.sleep(0.25)
    return False


def serve_readiness(port: int, world_size: int, *, timeout_s: int = 300) -> bool:
    """Coordinator-side barrier: block until world_size-1 DISTINCT worker
    ranks have checked in. Stray clients / resets are tolerated."""
    lib = _load_qacoord()
    if lib is not None:
        return lib.qacoord_serve(int(port), int(world_size), int(timeout_s)) == 0

    import socket
    import struct
    import time as _time

    # Global deadline: settimeout bounds each accept() individually, so
    # re-arm with the remaining time each iteration — stray clients must not
    # keep the barrier alive past timeout_s.
    deadline = _time.monotonic() + timeout_s
    with socket.socket() as listener:
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("", port))
        listener.listen(world_size + 8)
        seen: set = set()
        while len(seen) < world_size - 1:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                return False
            listener.settimeout(remaining)
            try:
                conn, _ = listener.accept()
            except socket.timeout:
                return False
            with conn:
                try:
                    # per-CONNECTION deadline (2s, clamped to the global one):
                    # settimeout bounds each recv individually and a byte-
                    # dripping client would re-arm it per byte, so re-derive
                    # the budget before every recv
                    conn_deadline = min(_time.monotonic() + 2.0, deadline)
                    hello = b""
                    while len(hello) < 5:
                        left = conn_deadline - _time.monotonic()
                        if left <= 0:
                            break
                        conn.settimeout(left)
                        chunk = conn.recv(5 - len(hello))
                        if not chunk:
                            break
                        hello += chunk
                    if len(hello) == 5 and hello[:1] == b"w":
                        conn.sendall(b"g")
                        seen.add(struct.unpack("!I", hello[1:])[0])
                except OSError:
                    continue  # reset/stray client — keep serving
    return True
