"""JAX API compatibility shims.

``shard_map`` graduated from ``jax.experimental.shard_map`` to
``jax.shard_map`` and renamed ``check_rep`` to ``check_vma`` along the
way. The repo targets the NEW spelling; this module backfills it on the
older runtimes the CI image pins (jax 0.4.x has neither ``jax.shard_map``
nor the ``check_vma`` keyword), so every caller — ring attention, the
pipeline island, tests — goes through ONE translation point instead of
each sprouting its own version probe.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "ensure_partitionable_threefry"]


def ensure_partitionable_threefry() -> None:
    """Make threefry bits a pure function of (key, logical index).

    The repo's mesh-invariance contract — the same seed yields the same
    dropout masks on ``data:2,seq:4`` and ``data:2,seq:2`` — requires
    index-keyed threefry bit generation. jax 0.4.x still defaults
    ``jax_threefry_partitionable`` to False, under which GSPMD lowers the
    bit sweep differently per mesh topology and trajectories drift a few
    percent under live dropout. Newer jax flipped the default; the update
    is then a no-op.
    """
    jax.config.update("jax_threefry_partitionable", True)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the new keyword surface on any jax version.

    On runtimes that ship ``jax.shard_map`` this is a pass-through; on
    0.4.x it maps to ``jax.experimental.shard_map.shard_map`` with
    ``check_vma`` translated to the old ``check_rep`` name (same meaning:
    replication/varying-axes checking of the body's outputs).
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    return _legacy_shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
