"""Post-training weight quantization: bf16/f32 checkpoints -> int8 trees.

Offline (numpy, host-side) half of the quantization subsystem: walks a
model's parameter tree and converts every matmul-dominant ``kernel`` leaf to
symmetric per-OUTPUT-channel int8 — ``kernel_q`` int8 ``[K, N]`` plus
``kernel_scale`` f32 ``[N]`` with ``kernel_q * kernel_scale ~= kernel`` —
the exact parameter structure ``quant.layers.QuantDense`` declares, so a
converted tree drops into a ``quantize='int8'`` model unchanged. Biases,
LayerNorm scales, embeddings and every other leaf pass through untouched:
they are VPU-side and a rounding error there buys nothing.

No retraining, no calibration data for the weight side (symmetric max-abs
per channel is exact enough at BERT scale — the per-layer error report
quantifies it), and checkpoints stay interchangeable: conversion happens at
engine startup (``compose.init_model(quantize='int8')``) or offline, always
FROM the ordinary float checkpoint format.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..ops.quant_matmul import INT8_MAX

# a weight column of exact zeros gets this scale (quantizes to zeros)
# instead of dividing by zero
_EPS = 1e-8

__all__ = [
    "quantize_kernel",
    "quantize_params",
    "param_bytes",
    "weight_kernel_bytes",
]


def quantize_kernel(w: np.ndarray, *, eps: float = _EPS
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-output-channel int8 quantization of one ``[K, N]``
    kernel: ``scale[n] = max|w[:, n]| / 127`` (floored at ``eps``),
    ``q = round_half_even(w / scale)`` clipped to ``[-127, 127]``.

    Round half to even matches the in-jit activation grid
    (``ops.quant_matmul.quantize_rowwise``); weights already ON the grid
    round-trip exactly (pinned in tests/test_quant.py).
    """
    wf = np.asarray(w, np.float32)
    if wf.ndim != 2:
        raise ValueError(f"quantize_kernel wants a 2D kernel, got {wf.shape}")
    amax = np.max(np.abs(wf), axis=0)
    scale = np.maximum(amax, eps) / INT8_MAX
    q = np.clip(np.rint(wf / scale[None, :]), -INT8_MAX, INT8_MAX)
    return q.astype(np.int8), scale.astype(np.float32)


def _leaf_bytes(leaf) -> int:
    # size/dtype come from array attributes — never np.asarray, which would
    # block on a full device->host copy per leaf (and raise outright on
    # non-fully-addressable sharded params)
    size = getattr(leaf, "size", None)
    dtype = getattr(leaf, "dtype", None)
    if size is None or dtype is None:
        arr = np.asarray(leaf)
        size, dtype = arr.size, arr.dtype
    return int(size) * int(np.dtype(dtype).itemsize)


def param_bytes(params) -> int:
    """Total bytes of every array leaf in a parameter tree (works on float
    and quantized trees alike — the serving-side weight-residency number the
    HBM pre-flight narrative and bench JSON report)."""
    total = 0
    stack = [params]
    while stack:
        node = stack.pop()
        if isinstance(node, dict):
            stack.extend(node.values())
        else:
            total += _leaf_bytes(node)
    return total


def weight_kernel_bytes(params) -> int:
    """Bytes of just the (quantizable or quantized) matmul kernels — the
    part int8 conversion actually shrinks."""
    total = 0
    stack = [params]
    while stack:
        node = stack.pop()
        if isinstance(node, dict):
            for name, child in node.items():
                if name in ("kernel", "kernel_q") and not isinstance(
                        child, dict):
                    total += _leaf_bytes(child)
                else:
                    stack.append(child)
    return total


def _quantize_node(node: dict, path: str, report: List[dict]) -> dict:
    out: Dict[str, object] = {}
    for name, child in node.items():
        sub = f"{path}/{name}" if path else name
        if isinstance(child, dict):
            out[name] = _quantize_node(child, sub, report)
            continue
        arr = np.asarray(child)
        if name == "kernel" and arr.ndim == 2:
            q, scale = quantize_kernel(arr)
            out["kernel_q"] = q
            out["kernel_scale"] = scale
            deq = q.astype(np.float32) * scale[None, :]
            wf = arr.astype(np.float32)
            err = deq - wf
            denom = float(np.sqrt(np.mean(wf ** 2))) or 1.0
            report.append({
                "layer": sub,
                "shape": list(arr.shape),
                "rms_err": float(np.sqrt(np.mean(err ** 2))),
                "max_abs_err": float(np.max(np.abs(err))),
                "rel_rms_err": float(np.sqrt(np.mean(err ** 2))) / denom,
            })
        else:
            out[name] = child
    return out


def quantize_params(params: dict) -> Tuple[dict, dict]:
    """Convert a float parameter tree to the int8 serving tree.

    Returns ``(qparams, report)``: every 2D ``kernel`` leaf becomes
    ``kernel_q``/``kernel_scale`` (QKV, attention-out, FFN, pooler, and the
    QA heads — everything matmul-shaped), all other leaves pass through by
    reference. The report carries the per-layer quantization error the
    calibration harness and ``bench.py`` surface, plus the weight-residency
    delta the serving HBM pre-flight benefits from.
    """
    layers: List[dict] = []
    qparams = _quantize_node(params, "", layers)
    report = {
        "quantize": "int8",
        "layers": layers,
        "n_quantized": len(layers),
        "orig_bytes": param_bytes(params),
        "quant_bytes": param_bytes(qparams),
        "orig_kernel_bytes": weight_kernel_bytes(params),
        "quant_kernel_bytes": weight_kernel_bytes(qparams),
        "max_rel_rms_err": max(
            (l["rel_rms_err"] for l in layers), default=0.0
        ),
    }
    return qparams, report
