"""``QuantDense``: the int8 drop-in for ``nn.Dense`` in quantized models.

Parameter structure matches what ``quant.quantize.quantize_params`` emits
from a float checkpoint — ``kernel_q`` int8 ``[K, N]``, ``kernel_scale``
f32 ``[N]``, ``bias`` f32 ``[N]`` (the bias passes through conversion
untouched) — under the SAME module names as the float model, so the only
difference between the trees is the kernel leaf pair. Init gives zero
kernels (compile/pre-flight shapes only); real weights always come from
conversion.

Forward: dynamic per-row activation quantization
(``ops.quant_matmul.quantize_rowwise``) then the fused int8 matmul
(``ops.quant_matmul.int8_matmul`` — MXU int8 contraction, exact integer
accumulation, fused f32 dequant-rescale), bias add in f32, cast to the
module compute dtype. The arithmetic is identical on every backend; only
the kernel-vs-XLA routing differs.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from ..ops.quant_matmul import int8_matmul, quantize_rowwise


def _int8_zeros(key, shape):
    del key
    return jnp.zeros(shape, jnp.int8)


class QuantDense(nn.Module):
    """Int8-weight Dense: ``y = dequant(act_q8 . kernel_q) + bias``."""

    features: int
    dtype: jnp.dtype = jnp.float32
    impl: str = "auto"  # int8_matmul routing: auto | pallas | emulate

    @nn.compact
    def __call__(self, x):
        K = x.shape[-1]
        kernel_q = self.param("kernel_q", _int8_zeros, (K, self.features))
        kernel_scale = self.param(
            "kernel_scale", nn.initializers.ones, (self.features,),
            jnp.float32,
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (self.features,), jnp.float32
        )
        x_q, x_scale = quantize_rowwise(x)
        y = int8_matmul(x_q, x_scale, kernel_q, kernel_scale, impl=self.impl)
        return (y + bias).astype(self.dtype)
