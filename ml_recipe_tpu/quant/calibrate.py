"""Calibration / parity harness: does the int8 path answer like bf16?

Post-training quantization has no training loop to absorb error, so the
subsystem ships its own measurement: run the SAME scoring forward
(``infer/score.build_score_fn`` — the one program batch inference and
serving both execute) through the float model and the quantized model on
identical inputs, and report end-to-end span-prediction agreement plus the
answerability-score drift. Together with the per-layer weight-error report
from ``quant.quantize.quantize_params`` this is the accept/reject evidence
for a quantized deployment; ``bench.py --mode serve/--mode infer`` surfaces
it in the JSON line and tier-1 pins it within an explicit tolerance on the
synthetic NQ fixture (tests/test_quant.py, tests/test_serve.py).
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import jax
import numpy as np

from ..infer.score import OUT_KEYS, build_score_fn

__all__ = ["make_parity_batches", "score_chunks", "span_parity"]


def make_parity_batches(
    tokenizer,
    lines: Sequence[dict],
    *,
    max_seq_len: int,
    max_question_len: int = 16,
    doc_stride: int = 128,
    batch_size: int = 8,
    limit: int = 64,
) -> List[Dict[str, np.ndarray]]:
    """Chunk synthetic NQ lines into serving-shaped host batches.

    Uses the engine's own request machinery (``data/chunking.py``:
    ``encode_document`` -> ``window_chunks`` -> ``assemble_input_ids``) so
    parity is measured on exactly the inputs traffic produces. Returns
    collate-shaped dicts of ``[batch_size, max_seq_len]`` planes (the
    trailing partial batch repeats its last row, predictor-style).
    """
    from ..data.chunking import (
        assemble_input_ids,
        encode_document,
        window_chunks,
    )

    cls_id = int(tokenizer.cls_token_id)
    sep_id = int(tokenizer.sep_token_id)
    pad_id = int(tokenizer.pad_token_id)

    rows: List[List[int]] = []
    for line in lines:
        enc_q = tokenizer.encode(line["question_text"])[:max_question_len]
        tokens, _, _ = encode_document(tokenizer, line["document_text"])
        for rec in window_chunks(
            tokens, ("unknown", -1, -1), question_len=len(enc_q),
            max_seq_len=max_seq_len, doc_stride=doc_stride,
        ):
            rows.append(assemble_input_ids(cls_id, sep_id, enc_q, rec))
            if len(rows) >= limit:
                break
        if len(rows) >= limit:
            break

    batches = []
    for at in range(0, len(rows), batch_size):
        group = rows[at: at + batch_size]
        group = group + [group[-1]] * (batch_size - len(group))
        ids = np.full((batch_size, max_seq_len), pad_id, np.int32)
        mask = np.zeros_like(ids)
        tt = np.zeros_like(ids)
        for i, row in enumerate(group):
            ids[i, : len(row)] = row
            mask[i, : len(row)] = 1
            seps = [j for j, t in enumerate(row) if t == sep_id]
            if seps:
                tt[i, seps[0] + 1: len(row)] = 1
        batches.append({
            "input_ids": ids, "attention_mask": mask, "token_type_ids": tt,
        })
    return batches


def score_chunks(model, params,
                 batches: Sequence[Dict[str, np.ndarray]]) -> np.ndarray:
    """Run the serving scoring forward over host batches; returns the
    concatenated packed output ``[6, n_rows]`` in ``OUT_KEYS`` order."""
    fwd = jax.jit(build_score_fn(model, wire_ids_only=False))
    outs = []
    for b in batches:
        planes = np.stack([
            np.asarray(b["input_ids"], np.int32),
            np.asarray(b["attention_mask"], np.int32),
            np.asarray(b["token_type_ids"], np.int32),
        ])
        outs.append(np.asarray(fwd(params, planes)))
    return np.concatenate(outs, axis=1) if outs else np.zeros((6, 0))


def span_parity(model, params, qmodel, qparams,
                batches: Sequence[Dict[str, np.ndarray]]) -> dict:
    """End-to-end agreement of the quantized scoring path vs the float one
    on identical inputs: span (start AND end) agreement fraction, label
    agreement, and answerability-score drift."""
    ref = score_chunks(model, params, batches)
    q = score_chunks(qmodel, qparams, batches)
    keys = {k: i for i, k in enumerate(OUT_KEYS)}
    n = ref.shape[1]
    if n == 0:
        return {"n_chunks": 0, "span_agreement": None,
                "label_agreement": None, "score_max_abs_delta": None,
                "score_mean_abs_delta": None}
    span_ok = np.logical_and(
        ref[keys["start_ids"]] == q[keys["start_ids"]],
        ref[keys["end_ids"]] == q[keys["end_ids"]],
    )
    label_ok = ref[keys["labels"]] == q[keys["labels"]]
    sdelta = np.abs(ref[keys["scores"]] - q[keys["scores"]])
    return {
        "n_chunks": int(n),
        "span_agreement": float(np.mean(span_ok)),
        "label_agreement": float(np.mean(label_ok)),
        "score_max_abs_delta": float(np.max(sdelta)),
        "score_mean_abs_delta": float(np.mean(sdelta)),
    }
