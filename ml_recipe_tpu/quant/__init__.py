"""Post-training int8 quantization subsystem (serving/inference only).

The serving forward has no gradient-precision constraint, and TPU MXU int8
peak is ~2x bf16 — this package converts any existing bf16/f32 checkpoint
to a symmetric per-channel int8 serving tree (no retraining, checkpoints
stay interchangeable) and measures what the conversion costs:

- ``quantize``: offline weight conversion + per-layer error report;
- ``layers``: the ``QuantDense`` module the quantized model executes
  (dynamic per-row activation scaling + the fused int8 matmul in
  ``ops/quant_matmul.py``);
- ``calibrate``: the end-to-end span-parity harness vs the float path.

``quantize_model`` is the one-call entry the CLIs and bench use: float
(model, params) in, (quantized model, quantized params, report) out.
"""

from __future__ import annotations

import dataclasses

from .calibrate import make_parity_batches, score_chunks, span_parity
from .layers import QuantDense
from .quantize import (
    param_bytes,
    quantize_kernel,
    quantize_params,
    weight_kernel_bytes,
)

__all__ = [
    "QuantDense",
    "make_parity_batches",
    "param_bytes",
    "quantize_kernel",
    "quantize_model",
    "quantize_params",
    "score_chunks",
    "span_parity",
    "weight_kernel_bytes",
]


def quantize_model(model, params, mode: str = "int8"):
    """Convert a float (model, params) pair to its int8 serving twin.

    Returns ``(qmodel, qparams, report)``: the model is the same module
    tree with ``quantize='int8'`` (every matmul Dense becomes
    ``QuantDense``), the params are the converted tree, the report is
    ``quantize_params``' per-layer error + byte accounting. ``mode='off'``
    is the identity (callers can wire a flag straight through).
    """
    if mode in (None, "off", False):
        return model, params, {"quantize": "off"}
    if mode != "int8":
        raise ValueError(f"unsupported quantization mode {mode!r} "
                         f"(want 'off' or 'int8')")
    qparams, report = quantize_params(params)
    qmodel = dataclasses.replace(model, quantize="int8")
    return qmodel, qparams, report
