"""Ring attention — sequence/context parallelism over the mesh ``seq`` axis.

No reference counterpart: the reference handles long documents purely by
data-level chunking (SURVEY.md §2.3 — sliding windows at
split_dataset.py:287-306). This op is the attention-level scale-out the TPU
framework adds: the sequence dimension is sharded over the ``seq`` mesh axis,
each device holds its local Q/K/V slice, and K/V blocks rotate around the
ring via ``ppermute`` while an online-softmax accumulator builds the exact
global attention — memory per device is O(L_local · L_local) instead of
O(L · L), and the K/V transfers ride the ICI ring concurrently with compute.

Algorithm: blockwise attention with running (max, denom, out) renormalisation
(Liu et al., "Ring Attention with Blockwise Transformers", arXiv 2310.01889 —
see PAPERS.md; implementation is original, written against the math).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _ring_attention_local(q, k, v, mask, *, axis_name: str, scale: float,
                          rate: float = 0.0, seed=None,
                          batch_axis: Optional[str] = None):
    """Per-shard body (runs under shard_map).

    q/k/v: [B, L_loc, H, D] local slices; mask: [B, L_loc] key validity.
    Returns [B, L_loc, H, D] — the exact softmax(QK^T)V rows for local Q
    against the FULL global K/V.

    Attention-probs dropout (``rate > 0``): keep-bits come from the shared
    :func:`ops.flash_attention.hash_uniform` finalizer keyed by the GLOBAL
    (batch, head, row, col) index — each rotating K/V block's global column
    offset is derived from the ring step, so the mask is independent of how
    many shards the sequence is split over, and identical whether computed
    here or in a single-device kernel. Matching torch semantics, the
    softmax DENOMINATOR is undropped; only the value-weighting probs are
    masked and inverse-scaled.
    """
    from .flash_attention import hash_uniform

    n_shards = jax.lax.psum(1, axis_name)
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    my_idx = jax.lax.axis_index(axis_name)

    B, L_loc, H, D = q.shape
    L_total = n_shards * L_loc

    if rate > 0.0:
        seed_val = seed[0].astype(jnp.int32)
        if batch_axis is not None:
            # decorrelate data-parallel groups: their local batch indices
            # overlap, so fold the dp coordinate into the seed
            seed_val = seed_val + jax.lax.axis_index(batch_axis) * jnp.int32(
                -1640531527
            )
        bh = (
            jnp.arange(B, dtype=jnp.int32)[:, None] * jnp.int32(H)
            + jnp.arange(H, dtype=jnp.int32)[None, :]
        )  # [B, H]
        row_ids = (my_idx * L_loc + jnp.arange(L_loc, dtype=jnp.int32))

    def keep_block(step):
        """[B, H, L_loc, L_loc] keep-bits for ring step ``step``: the block
        held now originated at shard (my_idx - step) mod n_shards."""
        col_off = ((my_idx - step) % n_shards) * L_loc
        col_ids = col_off + jnp.arange(L_loc, dtype=jnp.int32)
        x = row_ids[:, None] * jnp.int32(L_total) + col_ids[None, :]
        x = x[None, None, :, :] ^ (
            seed_val + bh[:, :, None, None] * jnp.int32(-1640531527)
        )
        return hash_uniform(x) >= rate

    def block_scores(k_blk, mask_blk):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk).astype(jnp.float32) * scale
        return jnp.where(mask_blk[:, None, None, :] > 0, s, _NEG_INF)

    def accumulate(carry, k_cur, v_cur, mask_cur, step):
        o_acc, m_acc, l_acc = carry

        s = block_scores(k_cur, mask_cur)                      # [B,H,Lq,Lk]
        m_blk = jnp.max(s, axis=-1)                            # [B,H,Lq]
        m_new = jnp.maximum(m_acc, m_blk)
        p = jnp.exp(s - m_new[..., None])                      # [B,H,Lq,Lk]
        corr = jnp.exp(m_acc - m_new)                          # [B,H,Lq]

        # the denominator accumulates UNdropped p (torch applies dropout
        # after softmax); only the value weighting is masked
        l_new = l_acc * corr + jnp.sum(p, axis=-1)
        if rate > 0.0:
            p_v = jnp.where(keep_block(step), p * (1.0 / (1.0 - rate)), 0.0)
        else:
            p_v = p
        o_blk = jnp.einsum("bhqk,bkhd->bqhd", p_v.astype(v_cur.dtype), v_cur)
        o_new = o_acc * corr.transpose(0, 2, 1)[..., None] + o_blk.astype(jnp.float32)
        return o_new, m_new, l_new

    def body(i, carry):
        acc, k_cur, v_cur, mask_cur = carry
        acc = accumulate(acc, k_cur, v_cur, mask_cur, i)
        # rotate K/V/mask one step around the ring (ICI neighbour copy,
        # overlapped with the next block's compute by the scheduler)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = jax.lax.ppermute(mask_cur, axis_name, perm)
        return acc, k_nxt, v_nxt, mask_nxt

    o0 = jnp.zeros((B, L_loc, H, D), jnp.float32)
    m0 = jnp.full((B, H, L_loc), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, L_loc), jnp.float32)

    # first n_shards-1 blocks rotate after accumulating; the final block
    # accumulates only — no wasted trailing ring transfer
    acc, k_last, v_last, mask_last = jax.lax.fori_loop(
        0, n_shards - 1, body, ((o0, m0, l0), k, v, mask)
    )
    o, m, l = accumulate(acc, k_last, v_last, mask_last, n_shards - 1)

    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]  # [B,Lq,H,1]
    return (o / denom).astype(q.dtype)


def ring_attention(
    q,
    k,
    v,
    mask=None,
    *,
    mesh: Mesh,
    axis_name: str = "seq",
    batch_axis: Optional[str] = None,
    dtype=jnp.float32,
    rate: float = 0.0,
    seed=None,
):
    """Exact global attention with Q/K/V sharded over ``axis_name``.

    Inputs are GLOBAL [B, L, H, D] arrays (sharded or not — shard_map
    partitions them); output is the global [B, L, H, D] attention result,
    sequence-sharded the same way. ``batch_axis`` names the mesh axis the
    batch dim is data-parallel over (composes dp x sp inside one jitted
    step); None replicates over any remaining axes.

    ``rate``/``seed``: attention-probs dropout applied in-flight during the
    ring sweep; the keep-mask is keyed by global indices, so results are
    invariant to the number of sequence shards.
    """
    if mask is None:
        mask = jnp.ones(q.shape[:2], dtype=jnp.int32)
    if seed is None:
        seed = jnp.zeros((1,), dtype=jnp.int32)

    scale = 1.0 / (q.shape[-1] ** 0.5)
    fn = functools.partial(
        _ring_attention_local, axis_name=axis_name, scale=scale,
        rate=rate, batch_axis=batch_axis,
    )

    seq_spec = P(batch_axis, axis_name, None, None)
    mask_spec = P(batch_axis, axis_name)

    return jax.shard_map(
        lambda q_, k_, v_, m_, s_: fn(q_, k_, v_, m_, seed=s_),
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, mask_spec, P(None)),
        out_specs=seq_spec,
        check_vma=False,
    )(q.astype(dtype), k.astype(dtype), v.astype(dtype), mask, seed)
