"""Ring attention — sequence/context parallelism over the mesh ``seq`` axis.

No reference counterpart: the reference handles long documents purely by
data-level chunking (SURVEY.md §2.3 — sliding windows at
split_dataset.py:287-306). This op is the attention-level scale-out the TPU
framework adds: the sequence dimension is sharded over the ``seq`` mesh axis,
each device holds its local Q/K/V slice, and K/V blocks rotate around the
ring via ``ppermute`` while an online-softmax accumulator builds the exact
global attention — memory per device is O(L_local · L_local) instead of
O(L · L), and the K/V transfers ride the ICI ring concurrently with compute.

Algorithm: blockwise attention with running (max, denom, out) renormalisation
(Liu et al., "Ring Attention with Blockwise Transformers", arXiv 2310.01889 —
see PAPERS.md; implementation is original, written against the math).

The BACKWARD is a custom VJP that recomputes each block's probabilities from
the saved per-row logsumexp and rotates (k, v, dk, dv) together around the
ring, so dk/dv partials arrive home after a full loop. Residuals are
O(L_local) per device (q, k, v, out, lse) — plain autodiff through the ring
loop would instead save every step's [B, H, L_loc, L_loc] probability block
plus rotated K/V copies, i.e. O(L_loc · L) per device, forfeiting exactly
the memory saving ring attention exists for (round-2 VERDICT missing #2).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.compat import shard_map

_NEG_INF = -1e30


def _dropout_ids(q_shape, *, axis_name: str, batch_axis: Optional[str], seed):
    """Global-index ingredients for the in-flight attention-probs dropout.

    Keep-bits come from the shared :func:`ops.flash_attention.hash_uniform`
    finalizer keyed by the GLOBAL (batch, head, row, col) index — each
    rotating K/V block's global column offset is derived from the ring step,
    so the mask is independent of how many shards the sequence is split
    over, and identical whether computed here or in a single-device kernel.
    """
    B, L_loc, H, _ = q_shape
    my_idx = jax.lax.axis_index(axis_name)
    seed_val = seed[0].astype(jnp.int32)
    if batch_axis is not None:
        # decorrelate data-parallel groups: their local batch indices
        # overlap, so fold the dp coordinate into the seed
        seed_val = seed_val + jax.lax.axis_index(batch_axis) * jnp.int32(
            -1640531527
        )
    bh = (
        jnp.arange(B, dtype=jnp.int32)[:, None] * jnp.int32(H)
        + jnp.arange(H, dtype=jnp.int32)[None, :]
    )  # [B, H]
    row_ids = my_idx * L_loc + jnp.arange(L_loc, dtype=jnp.int32)
    return seed_val, bh, row_ids


def _make_keep_block(q_shape, *, axis_name: str, batch_axis: Optional[str],
                     seed, rate: float, n_shards):
    """``keep_block(step) -> [B, H, L_loc, L_loc]`` keep-bits for the block
    held at ring step ``step`` (it originated at shard (my_idx - step) mod
    n_shards). Recomputed identically by forward and backward."""
    from .flash_attention import hash_uniform

    _, L_loc, _, _ = q_shape
    L_total = n_shards * L_loc
    my_idx = jax.lax.axis_index(axis_name)
    seed_val, bh, row_ids = _dropout_ids(
        q_shape, axis_name=axis_name, batch_axis=batch_axis, seed=seed
    )

    def keep_block(step):
        col_off = ((my_idx - step) % n_shards) * L_loc
        col_ids = col_off + jnp.arange(L_loc, dtype=jnp.int32)
        x = row_ids[:, None] * jnp.int32(L_total) + col_ids[None, :]
        x = x[None, None, :, :] ^ (
            seed_val + bh[:, :, None, None] * jnp.int32(-1640531527)
        )
        return hash_uniform(x) >= rate

    return keep_block


def _fwd_local(q, k, v, mask, seed, *, axis_name: str, scale: float,
               rate: float = 0.0, batch_axis: Optional[str] = None):
    """Per-shard forward (runs under shard_map).

    q/k/v: [B, L_loc, H, D] local slices; mask: [B, L_loc] key validity.
    Returns ``(out, lse)``: the exact softmax(QK^T)V rows for local Q
    against the FULL global K/V, and the per-row logsumexp [B, H, L_loc]
    the backward recomputes block probabilities from.

    Attention-probs dropout (``rate > 0``): matching torch semantics, the
    softmax DENOMINATOR is undropped; only the value-weighting probs are
    masked and inverse-scaled.
    """
    n_shards = jax.lax.psum(1, axis_name)
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    B, L_loc, H, D = q.shape

    if rate > 0.0:
        keep_block = _make_keep_block(
            q.shape, axis_name=axis_name, batch_axis=batch_axis,
            seed=seed, rate=rate, n_shards=n_shards,
        )

    def block_scores(k_blk, mask_blk):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk).astype(jnp.float32) * scale
        return jnp.where(mask_blk[:, None, None, :] > 0, s, _NEG_INF)

    def accumulate(carry, k_cur, v_cur, mask_cur, step):
        o_acc, m_acc, l_acc = carry

        s = block_scores(k_cur, mask_cur)                      # [B,H,Lq,Lk]
        m_blk = jnp.max(s, axis=-1)                            # [B,H,Lq]
        m_new = jnp.maximum(m_acc, m_blk)
        p = jnp.exp(s - m_new[..., None])                      # [B,H,Lq,Lk]
        corr = jnp.exp(m_acc - m_new)                          # [B,H,Lq]

        # the denominator accumulates UNdropped p (torch applies dropout
        # after softmax); only the value weighting is masked
        l_new = l_acc * corr + jnp.sum(p, axis=-1)
        if rate > 0.0:
            p_v = jnp.where(keep_block(step), p * (1.0 / (1.0 - rate)), 0.0)
        else:
            p_v = p
        o_blk = jnp.einsum("bhqk,bkhd->bqhd", p_v.astype(v_cur.dtype), v_cur)
        o_new = o_acc * corr.transpose(0, 2, 1)[..., None] + o_blk.astype(jnp.float32)
        return o_new, m_new, l_new

    def body(i, carry):
        acc, k_cur, v_cur, mask_cur = carry
        acc = accumulate(acc, k_cur, v_cur, mask_cur, i)
        # rotate K/V/mask one step around the ring (ICI neighbour copy,
        # overlapped with the next block's compute by the scheduler)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = jax.lax.ppermute(mask_cur, axis_name, perm)
        return acc, k_nxt, v_nxt, mask_nxt

    o0 = jnp.zeros((B, L_loc, H, D), jnp.float32)
    m0 = jnp.full((B, H, L_loc), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, L_loc), jnp.float32)

    # first n_shards-1 blocks rotate after accumulating; the final block
    # accumulates only — no wasted trailing ring transfer
    acc, k_last, v_last, mask_last = jax.lax.fori_loop(
        0, n_shards - 1, body, ((o0, m0, l0), k, v, mask)
    )
    o, m, l = accumulate(acc, k_last, v_last, mask_last, n_shards - 1)

    l_safe = jnp.maximum(l, 1e-30)
    denom = l_safe.transpose(0, 2, 1)[..., None]               # [B,Lq,H,1]
    lse = m + jnp.log(l_safe)                                  # [B,H,Lq]
    return (o / denom).astype(q.dtype), lse


def _bwd_local(q, k, v, mask, seed, out, lse, do, *, axis_name: str,
               scale: float, rate: float = 0.0,
               batch_axis: Optional[str] = None):
    """Per-shard blockwise-recompute backward (runs under shard_map).

    Each device owns its local Q rows (with ``do``/``out``/``lse`` local)
    and its local K/V columns. Per ring step: recompute the block's exact
    probabilities ``p = exp(s - lse)``, accumulate ``dq`` locally, add this
    device's contribution to the visiting block's ``dk``/``dv``, then rotate
    (k, v, mask, dk, dv) one hop — after a full loop every dk/dv partial is
    back at its owner. Nothing per-step is saved: peak extra memory is one
    [B, H, L_loc, L_loc] scratch block regardless of ring size.
    """
    n_shards = jax.lax.psum(1, axis_name)
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    if rate > 0.0:
        keep_block = _make_keep_block(
            q.shape, axis_name=axis_name, batch_axis=batch_axis,
            seed=seed, rate=rate, n_shards=n_shards,
        )
        inv_keep = 1.0 / (1.0 - rate)

    do_f = do.astype(jnp.float32)
    out_f = out.astype(jnp.float32)
    # D_i = sum_j P~_ij (dO_i . v_j) = dO_i . out_i (holds WITH dropout:
    # P_ij * keep_ij/(1-rate) is exactly the value-weighting P~_ij)
    D = jnp.einsum("bqhd,bqhd->bhq", do_f, out_f)              # [B,H,Lq]

    def block_grads(i, k_cur, v_cur, mask_cur):
        """(dq_blk, dk_blk, dv_blk) for the block held at ring step ``i``."""
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur).astype(jnp.float32) * scale
        s = jnp.where(mask_cur[:, None, None, :] > 0, s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])                        # [B,H,Lq,Lk]

        if rate > 0.0:
            keep = keep_block(i)
            p_v = jnp.where(keep, p * inv_keep, 0.0)
        else:
            p_v = p

        # dV_blk = P~^T dO ; dP~ = dO V^T ; dP = drop'(dP~)
        dv_blk = jnp.einsum("bhqk,bqhd->bkhd", p_v, do_f)
        dp_v = jnp.einsum("bqhd,bkhd->bhqk", do_f, v_cur.astype(jnp.float32))
        if rate > 0.0:
            dp = jnp.where(keep, dp_v * inv_keep, 0.0)
        else:
            dp = dp_v

        # softmax backward: ds = P (dP - D)
        ds = p * (dp - D[..., None])                           # [B,H,Lq,Lk]
        dq_blk = jnp.einsum("bhqk,bkhd->bqhd", ds, k_cur.astype(jnp.float32))
        dk_blk = jnp.einsum("bhqk,bqhd->bkhd", ds, q.astype(jnp.float32))
        return dq_blk * scale, dk_blk * scale, dv_blk

    def body(i, carry):
        dq_acc, k_cur, v_cur, mask_cur, dk_acc, dv_acc = carry
        dq_blk, dk_blk, dv_blk = block_grads(i, k_cur, v_cur, mask_cur)
        dq_acc = dq_acc + dq_blk
        dk_acc = dk_acc + dk_blk
        dv_acc = dv_acc + dv_blk

        # rotate the block AND its gradient partials together; after
        # n_shards hops each dk/dv block is home with every contribution
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = jax.lax.ppermute(mask_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_acc, axis_name, perm)
        return dq_acc, k_nxt, v_nxt, mask_nxt, dk_nxt, dv_nxt

    B, L_loc, H, Dh = q.shape
    zeros = lambda: jnp.zeros((B, L_loc, H, Dh), jnp.float32)  # noqa: E731
    # last step peeled (like the forward): the final k/v/mask rotation would
    # feed no further compute — only dk/dv still need their homeward hop
    dq, k_last, v_last, mask_last, dk, dv = jax.lax.fori_loop(
        0, n_shards - 1, body, (zeros(), k, v, mask, zeros(), zeros())
    )
    dq_blk, dk_blk, dv_blk = block_grads(n_shards - 1, k_last, v_last, mask_last)
    dq = dq + dq_blk
    dk = jax.lax.ppermute(dk + dk_blk, axis_name, perm)
    dv = jax.lax.ppermute(dv + dv_blk, axis_name, perm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _stream_row_seeds(seed, *, B: int, H: int, dp_size: int):
    """GLOBAL per-row dropout seeds for the composed inner, [B] int32.

    Built OUTSIDE the shard_map and sharded over ``batch_axis`` so the
    composed path never calls ``axis_index`` — XLA's constant sinking
    clones a ``partition-id``-derived pallas operand into while-loop
    bodies, where the SPMD partitioner rejects it (the dense inner's
    in-shard fold never feeds a pallas call, so it is unaffected).

    Bit-compatible with the dense ring AND the single-chip streaming
    kernels: row ``b`` of dp group ``r`` gets ``seed + r*PRIME +
    b_local*H*PRIME`` — exactly ``_row_seeds`` applied to the dense
    path's dp-folded seed (the kernel adds the per-head ``h*PRIME``)."""
    prime = jnp.int32(-1640531527)
    rows = jnp.arange(B, dtype=jnp.int32)
    b_loc = B // dp_size
    return (
        seed[0].astype(jnp.int32)
        + (rows // b_loc) * prime
        + (rows % b_loc) * jnp.int32(H) * prime
    )


def _merge_hop(o_acc, lse_acc, out_hop, lse_hop):
    """Fold one hop's normalized streaming output into the running global
    accumulator. Each hop's kernel returns ``out_hop = N_hop / l_hop`` and
    ``lse_hop = log(sum_k e^s)`` over the visiting block only, so

        out_global = sum_hop out_hop * exp(lse_hop - lse_global)

    with ``lse_global = logaddexp over hops`` — exact online-softmax
    across the ``ppermute`` rotation (the within-hop sweep already merged
    inside the kernel). Holds verbatim under torch-semantics dropout: the
    undropped denominator is exactly what ``lse`` carries. An all-masked
    hop arrives with ``lse_hop`` ~ -1e30 and merges with weight zero."""
    lse_new = jnp.logaddexp(lse_acc, lse_hop)                  # [B,H,Lq]
    w_acc = jnp.exp(lse_acc - lse_new).transpose(0, 2, 1)[..., None]
    w_hop = jnp.exp(lse_hop - lse_new).transpose(0, 2, 1)[..., None]
    return o_acc * w_acc + out_hop.astype(jnp.float32) * w_hop, lse_new


def _stream_fwd_local(q, k, v, mask, seed, spos, *, axis_name: str,
                      rate: float, batch_axis: Optional[str],
                      blk: int, hc: int, interpret: bool, seg: bool):
    """Composed streaming-ring forward (runs under shard_map).

    Per hop the visiting K/V shard is consumed by the streaming Pallas
    forward — per-device activation scratch is O(blk^2) per program
    instead of the dense inner's O(L_loc^2) block — and the online-softmax
    state carries across hops via ``_merge_hop``. Dropout keep-bits are
    keyed by ABSOLUTE (row, col) against the GLOBAL length, bit-identical
    to the dense ring inner and to a single-chip streaming kernel.

    ``seed``: per-row [B] seeds (``_stream_row_seeds``, dp fold baked in).
    ``spos``: this shard's [L_loc] slice of the global position iota —
    ``spos[0]`` is the absolute q-row base, and a copy of it ROTATES with
    the K/V block (each visiting block carries its own absolute column
    offset home), so no ``axis_index`` value ever feeds the kernels.

    ``seg``: ``mask`` carries segment ids; the q-side ids stay resident
    while the k-side copy rotates, concatenated per hop into the
    ``seg_split`` kernel operand. Unsegmented, ``mask`` is the rotating
    key-validity row.
    """
    from .flash_streaming import _stream_forward

    n_shards = jax.lax.psum(1, axis_name)
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    B, L_loc, H, D = q.shape
    row_base = spos[:1].astype(jnp.int32)

    def hop(k_cur, v_cur, mask_cur, col_base):
        mask_arg = (
            jnp.concatenate([mask, mask_cur], axis=1) if seg else mask_cur
        )
        return _stream_forward(
            q, k_cur, v_cur, mask_arg, seed, blk, hc, jnp.float32,
            rate, interpret, seg=seg,
            base=jnp.concatenate([row_base, col_base]),
            L_hash=n_shards * L_loc, seg_split=seg,
        )

    def body(i, carry):
        o_acc, lse_acc, k_cur, v_cur, mask_cur, col_cur = carry
        out_hop, lse_hop = hop(k_cur, v_cur, mask_cur, col_cur)
        o_acc, lse_acc = _merge_hop(o_acc, lse_acc, out_hop, lse_hop)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = jax.lax.ppermute(mask_cur, axis_name, perm)
        col_nxt = jax.lax.ppermute(col_cur, axis_name, perm)
        return o_acc, lse_acc, k_nxt, v_nxt, mask_nxt, col_nxt

    o0 = jnp.zeros((B, L_loc, H, D), jnp.float32)
    lse0 = jnp.full((B, H, L_loc), _NEG_INF, jnp.float32)
    o, lse, k_last, v_last, mask_last, col_last = jax.lax.fori_loop(
        0, n_shards - 1, body, (o0, lse0, k, v, mask, row_base)
    )
    out_hop, lse_hop = hop(k_last, v_last, mask_last, col_last)
    o, lse = _merge_hop(o, lse, out_hop, lse_hop)
    return o.astype(q.dtype), lse


def _stream_bwd_local(q, k, v, mask, seed, spos, out, lse, do, *,
                      axis_name: str, rate: float,
                      batch_axis: Optional[str],
                      blk: int, hc: int, interpret: bool, seg: bool):
    """Composed streaming-ring backward (runs under shard_map).

    The GLOBAL per-row ``lse`` (and global-normalized ``out``) saved by the
    forward let every hop recompute its block's exact probabilities
    ``p = exp(s - lse)`` inside the streaming dq/dk/dv kernels — no
    per-hop renormalisation chain. ``dq`` sums over hops locally in f32;
    ``dk``/``dv`` partials accumulate in a carry that rotates home with
    the visiting block (last hop peeled, one final homeward ``ppermute``,
    exactly the dense inner's schedule). ``seed``/``spos`` as in
    ``_stream_fwd_local``: per-row seeds and the sharded position iota,
    with the column base rotating alongside the visiting block."""
    from .flash_streaming import _stream_backward

    n_shards = jax.lax.psum(1, axis_name)
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    B, L_loc, H, D = q.shape
    row_base = spos[:1].astype(jnp.int32)

    def hop_grads(k_cur, v_cur, mask_cur, col_base):
        mask_arg = (
            jnp.concatenate([mask, mask_cur], axis=1) if seg else mask_cur
        )
        return _stream_backward(
            q, k_cur, v_cur, mask_arg, seed, do, out, lse, blk, hc,
            jnp.float32, rate, interpret, seg=seg,
            base=jnp.concatenate([row_base, col_base]),
            L_hash=n_shards * L_loc, seg_split=seg,
        )

    def body(i, carry):
        dq_acc, k_cur, v_cur, mask_cur, col_cur, dk_acc, dv_acc = carry
        dq_h, dk_h, dv_h = hop_grads(k_cur, v_cur, mask_cur, col_cur)
        dq_acc = dq_acc + dq_h.astype(jnp.float32)
        dk_acc = dk_acc + dk_h.astype(jnp.float32)
        dv_acc = dv_acc + dv_h.astype(jnp.float32)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = jax.lax.ppermute(mask_cur, axis_name, perm)
        col_nxt = jax.lax.ppermute(col_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_acc, axis_name, perm)
        return dq_acc, k_nxt, v_nxt, mask_nxt, col_nxt, dk_nxt, dv_nxt

    zeros = lambda: jnp.zeros((B, L_loc, H, D), jnp.float32)  # noqa: E731
    dq, k_last, v_last, mask_last, col_last, dk, dv = jax.lax.fori_loop(
        0, n_shards - 1, body,
        (zeros(), k, v, mask, row_base, zeros(), zeros()),
    )
    dq_h, dk_h, dv_h = hop_grads(k_last, v_last, mask_last, col_last)
    dq = dq + dq_h.astype(jnp.float32)
    dk = jax.lax.ppermute(dk + dk_h.astype(jnp.float32), axis_name, perm)
    dv = jax.lax.ppermute(dv + dv_h.astype(jnp.float32), axis_name, perm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def ring_stream_geometry(L_loc: int, H: int, D: int, dtype, rate: float,
                         *, segmented: bool = False,
                         interpret: bool = False):
    """(blk, hc) for the composed streaming-ring inner at LOCAL length
    ``L_loc``, or None when no legal streaming geometry exists (the caller
    falls back to the dense inner). Keys the autotune cache with the
    ``-ring`` suffix so single-chip picks are never reused."""
    from .flash_streaming import _streaming_geometry

    return _streaming_geometry(
        L_loc, H, D, jnp.dtype(dtype), jnp.dtype(jnp.float32), rate,
        mask_dtype=jnp.int32, interpret=interpret,
        seg=segmented, ring=True,
    )


def ring_attention(
    q,
    k,
    v,
    mask=None,
    *,
    mesh: Mesh,
    axis_name: str = "seq",
    batch_axis: Optional[str] = None,
    dtype=jnp.float32,
    rate: float = 0.0,
    seed=None,
    custom_backward: bool = True,
    segment_ids=None,
    inner: str = "auto",
    interpret: bool = False,
):
    """Exact global attention with Q/K/V sharded over ``axis_name``.

    Inputs are GLOBAL [B, L, H, D] arrays (sharded or not — shard_map
    partitions them); output is the global [B, L, H, D] attention result,
    sequence-sharded the same way. ``batch_axis`` names the mesh axis the
    batch dim is data-parallel over (composes dp x sp inside one jitted
    step); None replicates over any remaining axes.

    ``rate``/``seed``: attention-probs dropout applied in-flight during the
    ring sweep; the keep-mask is keyed by global indices, so results are
    invariant to the number of sequence shards.

    ``custom_backward``: use the blockwise-recompute VJP (O(L_local)
    residuals). False falls back to plain autodiff through the ring loop —
    kept as the differential-testing oracle (it stores every ring step's
    probability block: correct, but O(L_local · L) memory).

    ``inner``: 'auto' consumes each visiting K/V shard through the
    streaming Pallas kernels when a legal (blk, hc) geometry exists at the
    local length (per-device activation scratch O(blk^2) instead of the
    dense inner's O(L_loc^2)), falling back to the dense inner otherwise.
    'stream' requires the composed path (raises without a geometry);
    'dense' forces the historical inner. Results are identical up to f32
    reduction reordering — dropout masks bit-identical — across inners.

    ``segment_ids``: optional [B, L] packed-segment ids (0 = pad); needs
    the composed streaming inner (the dense inner is unsegmented).
    ``interpret``: run the streaming kernels in Pallas interpret mode
    (forced automatically off-TPU).
    """
    if mask is None:
        mask = jnp.ones(q.shape[:2], dtype=jnp.int32)
    if seed is None:
        seed = jnp.zeros((1,), dtype=jnp.int32)

    seg = segment_ids is not None
    B, L, H, D = q.shape
    n_shards = int(mesh.shape[axis_name])
    scale = 1.0 / (D ** 0.5)

    stream_cfg = None
    if inner in ("auto", "stream") and custom_backward and L % n_shards == 0:
        interpret = bool(interpret) or jax.default_backend() != "tpu"
        stream_cfg = ring_stream_geometry(
            L // n_shards, H, D, dtype, rate, segmented=seg,
            interpret=interpret,
        )
    if stream_cfg is None:
        if inner == "stream":
            raise ValueError(
                f"no legal streaming geometry for the composed ring inner "
                f"at L_loc={L // n_shards}, H={H}, D={D} (rate={rate}); "
                f"use inner='dense' or a longer sequence"
            )
        if seg:
            raise NotImplementedError(
                "segment_ids require the composed streaming-ring inner "
                "(no legal geometry at this shape, or inner='dense'/"
                "custom_backward=False was forced); the dense ring inner "
                "is unsegmented"
            )

    seq_spec = P(batch_axis, axis_name, None, None)
    mask_spec = P(batch_axis, axis_name)
    lse_spec = P(batch_axis, None, axis_name)

    # The composed inner never calls ``axis_index``: the per-row dropout
    # seeds fold the dp rank OUTSIDE the shard_map (sharding the [B] row
    # over ``batch_axis`` hands each dp group exactly the dense path's
    # in-shard fold), and absolute (row, col) bases come from a sharded
    # position iota whose column copy ppermutes with the visiting K/V
    # block. XLA's constant sinking clones ``partition-id``-derived pallas
    # operands into while-loop bodies, where the SPMD partitioner rejects
    # them — so no kernel operand may depend on it.
    if stream_cfg is not None:
        blk, hc = stream_cfg
        common = dict(axis_name=axis_name, rate=rate, batch_axis=batch_axis,
                      blk=blk, hc=hc, interpret=interpret, seg=seg)
        mask = (
            jnp.where(mask > 0, segment_ids.astype(jnp.int32), 0)
            if seg else mask
        )
        local_fwd, local_bwd = _stream_fwd_local, _stream_bwd_local
        dp_size = int(mesh.shape[batch_axis]) if batch_axis is not None else 1
        seed_arg = _stream_row_seeds(seed, B=B, H=H, dp_size=dp_size)
        seed_spec = P(batch_axis)
    else:
        common = dict(axis_name=axis_name, scale=scale, rate=rate,
                      batch_axis=batch_axis)

        def local_fwd(q_, k_, v_, mask_, seed_, spos_, **kw):
            return _fwd_local(q_, k_, v_, mask_, seed_, **kw)

        def local_bwd(q_, k_, v_, mask_, seed_, spos_, out_, lse_, do_,
                      **kw):
            return _bwd_local(q_, k_, v_, mask_, seed_, out_, lse_, do_,
                              **kw)

        seed_arg, seed_spec = seed, P(None)

    spos = jnp.arange(L, dtype=jnp.int32)
    spos_spec = P(axis_name)

    fwd_sm = shard_map(
        functools.partial(local_fwd, **common),
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, mask_spec, seed_spec,
                  spos_spec),
        out_specs=(seq_spec, lse_spec),
        check_vma=False,
    )

    q, k, v = q.astype(dtype), k.astype(dtype), v.astype(dtype)

    if not custom_backward:
        return fwd_sm(q, k, v, mask, seed_arg, spos)[0]

    bwd_sm = shard_map(
        functools.partial(local_bwd, **common),
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, mask_spec, seed_spec,
                  spos_spec, seq_spec, lse_spec, seq_spec),
        out_specs=(seq_spec, seq_spec, seq_spec),
        check_vma=False,
    )

    @jax.custom_vjp
    def attn(q_, k_, v_, mask_, seed_, spos_):
        return fwd_sm(q_, k_, v_, mask_, seed_, spos_)[0]

    def attn_fwd(q_, k_, v_, mask_, seed_, spos_):
        out, lse = fwd_sm(q_, k_, v_, mask_, seed_, spos_)
        return out, (q_, k_, v_, mask_, seed_, spos_, out, lse)

    def attn_bwd(res, do):
        q_, k_, v_, mask_, seed_, spos_, out, lse = res
        dq, dk, dv = bwd_sm(q_, k_, v_, mask_, seed_, spos_, out, lse, do)
        return dq, dk, dv, None, None, None

    attn.defvjp(attn_fwd, attn_bwd)
    return attn(q, k, v, mask, seed_arg, spos)
