"""Ahead-of-time compiled-program artifact store: zero-compile warm restarts.

The goodput ledger (PR 13) measured ``compile_warmup`` as the single largest
badput category on the smoke runs, and every supervisor restart, elastic
shrunk-mesh resume (PR 16) and serving rolling restart re-pays XLA
compilation for the whole program set. The pjit/TPUv4 systems work (arxiv
2204.06514, PAPERS.md) treats persistent compilation caching as a
first-class discipline for exactly this reason; TorchTitan (arxiv
2410.06511) frames fast restart as what makes preemptible capacity usable.

This module generalizes the PR-2 autotune cache (per-device-kind geometry
WINNERS in ``artifacts/tuning/*.json``) into a store of the compiled
PROGRAMS themselves: :meth:`ProgramCache.load_or_compile` performs
``jit(...).lower(...).compile()`` once, serializes the executable via
``jax.experimental.serialize_executable``, and on the next process —
a restarted trainer, a rolling-restarted serving replica — deserializes it
instead of compiling. Backends whose runtime cannot (de)serialize degrade
loudly to plain recompilation; training and serving semantics never depend
on the store.

Artifact anatomy (one file per program under
``<cache_dir>/<device_kind>/``):

- filename = ``<name>--<geometry>--<plan>--<extra>.aot`` — the LOOKUP key:
  program name, bucket/batch geometry, `ParallelPlan` mesh axes, and the
  precision/model suffix (the ``-q8`` discipline of ops/quant_matmul.py);
- content = magic + one JSON header line + the pickled
  ``serialize_executable`` payload. The header carries the VALIDITY
  fingerprint — ``code`` (package source hash + ``MLRT_AOT_SALT``),
  ``jax`` / ``jaxlib`` versions, and ``hlo`` (a hash of the lowered
  StableHLO text, so ANY semantic change to the program — a different
  learning-rate closure, another batch_split — invalidates exactly) —
  plus the blob's length and sha256 for corrupt/truncation recovery.

A stale fingerprint MISSES loudly (one structured log line naming the
changed component) and recompiles; a corrupt or truncated blob is deleted
and recompiled; writes go through ``metrics.artifacts.atomic_write_bytes``
(tmp + rename) so a concurrently warming process never reads a torn blob.
``--aot_cache off`` (or an absent store) leaves every call site compiling
exactly what HEAD compiled.

The inspection CLI lives in ``__main__``::

    python -m ml_recipe_tpu.ops.aot --list
    python -m ml_recipe_tpu.ops.aot --verify
    python -m ml_recipe_tpu.ops.aot --evict --aot_cache_bytes 512M

and is stdlib-only (no jax import): it must run on a host that merely
ADMINISTERS the store.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import logging
import os
import pickle
import re
import threading
import time
from contextlib import contextmanager
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Tuple

logger = logging.getLogger(__name__)

_MAGIC = b"MLRTAOT1\n"
_STORE_VERSION = 1

# "0"/"false"/"off" disables the store process-wide (plain recompilation)
ENV_ENABLED = "MLRT_AOT"
# cache-directory override (tests point this at a tmp dir so tier-1 never
# writes into the repo's artifacts/)
ENV_CACHE_DIR = "MLRT_AOT_CACHE"
# byte budget for the store (K/M/G suffixes); unset/0 = unbounded
ENV_CACHE_BYTES = "MLRT_AOT_CACHE_BYTES"
# extra fingerprint salt: a fleet-wide invalidation lever that needs no
# source change (and the regression tests' stale-fingerprint mutation hook)
ENV_SALT = "MLRT_AOT_SALT"

# validity components, compared in this order on lookup
FINGERPRINT_COMPONENTS = ("code", "jax", "jaxlib", "hlo")


def default_cache_dir() -> Path:
    env = os.environ.get(ENV_CACHE_DIR)
    if env:
        return Path(env)
    return Path(__file__).resolve().parents[2] / "artifacts" / "aot"


def _env_enabled() -> bool:
    return os.environ.get(ENV_ENABLED, "1").strip().lower() not in (
        "0", "false", "off", "no",
    )


def parse_bytes(text) -> Optional[int]:
    """``'512M'`` -> 536870912. None/''/0 -> None (unbounded). Accepts
    K/M/G suffixes (binary units) and plain byte counts."""
    if text is None:
        return None
    if isinstance(text, (int, float)):
        value = int(text)
        return value if value > 0 else None
    text = str(text).strip()
    if not text:
        return None
    match = re.fullmatch(r"(\d+)\s*([kKmMgG]?)[bB]?", text)
    if not match:
        raise ValueError(
            f"unparseable byte budget {text!r} (want e.g. 512M, 2G, 1048576)"
        )
    value = int(match.group(1))
    scale = {"": 1, "k": 1 << 10, "m": 1 << 20, "g": 1 << 30}[
        match.group(2).lower()
    ]
    value *= scale
    return value if value > 0 else None


def _device_kind() -> str:
    """Store partition key — the accelerator generation, exactly the
    autotune cache's discipline (a program compiled for one chip must
    never be deserialized on another)."""
    from . import autotune

    return autotune._device_kind()


def _jax_versions() -> Tuple[str, str]:
    try:
        import jax
        import jaxlib

        jl = getattr(jaxlib, "__version__", None) or getattr(
            getattr(jaxlib, "version", None), "__version__", "?"
        )
        return str(jax.__version__), str(jl)
    except Exception:  # noqa: BLE001 - no version = never match = recompile
        return "unknown", "unknown"


_CODE_FP: Optional[str] = None


def _code_fingerprint() -> str:
    """Hash of the package's Python source (memoized per process), mixed
    with ``MLRT_AOT_SALT`` — the salt is read per call so a test (or an
    operator forcing fleet-wide invalidation) can flip it without a new
    process."""
    global _CODE_FP
    if _CODE_FP is None:
        digest = hashlib.sha256()
        root = Path(__file__).resolve().parents[1]
        for path in sorted(root.rglob("*.py")):
            try:
                digest.update(path.relative_to(root).as_posix().encode())
                digest.update(path.read_bytes())
            except OSError:
                continue
        _CODE_FP = digest.hexdigest()[:16]
    salt = os.environ.get(ENV_SALT, "")
    if salt:
        return hashlib.sha256(
            f"{_CODE_FP}+{salt}".encode()
        ).hexdigest()[:16]
    return _CODE_FP


def _sanitize_part(part) -> str:
    """Filename-safe key component (MAY be empty — emptiness is part of
    the key: ``(geometry='', plan='x')`` must not collide with
    ``(geometry='x', plan='')``)."""
    return re.sub(r"[^A-Za-z0-9._-]+", "_", str(part))


def plan_signature(plan) -> str:
    """Stable mesh-axes key component from a ``ParallelPlan`` (or
    anything with ``describe() -> {axis: size}``), e.g. ``data4-model2``.
    Axis ORDER is part of the signature — it is the mesh order."""
    describe = getattr(plan, "describe", None)
    axes = describe() if callable(describe) else plan
    if isinstance(axes, dict):
        return "-".join(f"{k}{v}" for k, v in axes.items())
    return str(axes or "")


# -- serialization adapters (monkeypatch points for the unsupported-backend
# -- tests: a backend that cannot serialize raises here, never crashes a run)

def _serialize(compiled):
    from jax.experimental import serialize_executable

    return serialize_executable.serialize(compiled)


@contextmanager
def _genuine_compile():
    """Compile with jax's own persistent compilation cache suspended.

    An executable that cache served (deserialized from
    ``JAX_COMPILATION_CACHE_DIR``) re-serializes to a payload that
    references compiled symbols it does not carry — deserializing it later
    fails with ``Symbols not found``. A store-bound compile must therefore
    be genuine, or a warm XLA cache would silently keep the program store
    empty (the write-validation in :meth:`ProgramCache._store` would
    refuse every blob). The jit dispatch cache is unaffected; this only
    bypasses the cross-process disk cache for the one compile the store
    is about to own.

    Flipping ``jax_enable_compilation_cache`` alone is not enough: the
    compiler gates on ``compilation_cache.is_cache_used(backend)``, which
    latches its verdict in module globals the first time any compile
    consults the cache. ``reset_cache()`` is the documented way to drop
    that latch, so it is called after each toggle — once so the flag-off
    compile re-probes (and skips) the cache, once so later non-store
    compiles re-probe with it enabled again."""
    try:
        import jax

        prev = bool(jax.config.jax_enable_compilation_cache)
    except Exception:  # noqa: BLE001 - no jax config = nothing to suspend
        yield
        return
    if not prev:
        yield
        return

    def _drop_latch():
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception as e:  # noqa: BLE001 - private API; best effort,
            # the write-validation in _store backstops correctness
            logger.debug("AOT: compilation_cache.reset_cache failed: %s", e)

    jax.config.update("jax_enable_compilation_cache", False)
    _drop_latch()
    try:
        yield
    finally:
        jax.config.update("jax_enable_compilation_cache", True)
        _drop_latch()


def _deserialize(payload):
    from jax.experimental import serialize_executable

    serialized, in_tree, out_tree = payload
    return serialize_executable.deserialize_and_load(
        serialized, in_tree, out_tree
    )


# -- artifact file I/O ---------------------------------------------------------

def _read_artifact(path: Path):
    """``(header, blob, problem)``: problem is None when the artifact is
    structurally sound, ``'absent'`` when missing, else a human-readable
    corruption verdict (bad magic / torn header / truncated or
    checksum-failed blob) — the ``--verify`` CLI prints these verbatim."""
    try:
        raw = path.read_bytes()
    except FileNotFoundError:
        return None, None, "absent"
    except OSError as e:
        return None, None, f"unreadable ({e})"
    if not raw.startswith(_MAGIC):
        return None, None, "corrupt (bad magic)"
    try:
        end = raw.index(b"\n", len(_MAGIC))
        header = json.loads(raw[len(_MAGIC):end])
    except ValueError:
        return None, None, "corrupt (torn header)"
    if not isinstance(header, dict):
        return None, None, "corrupt (header is not an object)"
    blob = raw[end + 1:]
    want = header.get("blob_bytes")
    if want != len(blob):
        return None, None, (
            f"corrupt (truncated: {len(blob)} of {want} blob bytes)"
        )
    if header.get("blob_sha256") != hashlib.sha256(blob).hexdigest():
        return None, None, "corrupt (blob checksum mismatch)"
    return header, blob, None


def _iter_artifacts(cache_dir: Path) -> Iterator[Tuple[Path, Optional[dict], Optional[str]]]:
    """Every ``*.aot`` under the store, with its parsed header (or the
    corruption verdict)."""
    root = Path(cache_dir)
    if not root.is_dir():
        return
    for path in sorted(root.rglob("*.aot")):
        header, _, problem = _read_artifact(path)
        yield path, header, problem


def evict_to_budget(cache_dir, budget_bytes: Optional[int]) -> List[Path]:
    """Prune oldest-first (mtime) until the store's ``*.aot`` total fits
    ``budget_bytes``; returns the removed paths. No-op when unbounded."""
    if not budget_bytes or budget_bytes <= 0:
        return []
    root = Path(cache_dir)
    if not root.is_dir():
        return []
    entries = []
    for path in root.rglob("*.aot"):
        try:
            st = path.stat()
        except OSError:
            continue
        entries.append((st.st_mtime, st.st_size, path))
    total = sum(size for _, size, _ in entries)
    removed: List[Path] = []
    for _, size, path in sorted(entries):
        if total <= budget_bytes:
            break
        try:
            path.unlink()
        except OSError:
            continue
        total -= size
        removed.append(path)
    if removed:
        logger.info(
            "AOT: evicted %d artifact(s) to fit the %d-byte budget "
            "(store now %d bytes).", len(removed), budget_bytes, total,
        )
    return removed


def verify_store(cache_dir) -> List[dict]:
    """``--verify``'s engine: one report row per artifact — corrupt blobs
    are REPORTED (status carries the verdict), never silently deleted, so
    warmup does not trip on them and an operator sees why."""
    rows = []
    for path, header, problem in _iter_artifacts(cache_dir):
        try:
            size = path.stat().st_size
        except OSError:
            size = None
        rows.append({
            "path": str(path),
            "status": "ok" if problem is None else problem,
            "bytes": size,
            "fingerprint": (header or {}).get("fingerprint"),
        })
    return rows


# -- the store -----------------------------------------------------------------

class ProgramCache:
    """Process-wide AOT compiled-program store: lower -> (load | compile
    -> serialize) keyed by (device kind, program name, geometry, plan
    axes, extra) and fingerprint-validated by (code, jax, jaxlib, hlo).

    ``hits`` count disk loads that produced a running executable without
    an XLA compile; ``misses`` count real compiles while the store was
    active (the zero-compile warm-restart drills pin these); ``bypass``
    counts compiles with the store disabled (``--aot_cache off`` — the
    HEAD-identical path).
    """

    def __init__(self, cache_dir: Optional[Path] = None,
                 enabled: Optional[bool] = None,
                 cache_bytes: Optional[int] = None):
        self.enabled = _env_enabled() if enabled is None else enabled
        self._cache_dir = Path(cache_dir) if cache_dir else None
        self.cache_bytes = (
            cache_bytes if cache_bytes is not None
            else parse_bytes(os.environ.get(ENV_CACHE_BYTES))
        )
        self.hits = 0
        self.misses = 0
        self.bypass = 0
        self.evictions = 0
        self.load_times_s: List[float] = []
        self._session: List[dict] = []
        # loud-once latch: a backend that cannot serialize fails every
        # attempt — warn at the first, stop paying serialize cost after
        self._serialize_unsupported = False
        self._lock = threading.RLock()

    # -- configuration ---------------------------------------------------------

    @property
    def cache_dir(self) -> Path:
        # resolved lazily so an env override set after import still applies
        return self._cache_dir if self._cache_dir else default_cache_dir()

    def set_cache_dir(self, cache_dir) -> None:
        with self._lock:
            self._cache_dir = Path(cache_dir) if cache_dir else None

    # -- the one entry point ---------------------------------------------------

    def load_or_compile(self, name: str, jit_fn, *args, geometry: str = "",
                        plan: str = "", extra: str = "",
                        key_by_hlo: bool = False):
        """The compiled executable for ``jit_fn`` at ``args`` — loaded
        from the store when a valid artifact exists, compiled (and
        stored) otherwise. See :meth:`load_or_compile_ex` for the
        outcome-reporting variant."""
        return self.load_or_compile_ex(
            name, jit_fn, *args, geometry=geometry, plan=plan, extra=extra,
            key_by_hlo=key_by_hlo,
        )[0]

    def load_or_compile_ex(self, name: str, jit_fn, *args,
                           geometry: str = "", plan: str = "",
                           extra: str = "", key_by_hlo: bool = False):
        """``(compiled, outcome, seconds)`` with outcome one of
        ``'hit'`` (deserialized, zero XLA compile), ``'miss'`` (compiled;
        stale/corrupt/absent/deserialize-failed artifact) or ``'bypass'``
        (store disabled — the HEAD-identical compile).

        Compile errors PROPAGATE: the fused-kernel probes
        (quant_matmul/flash_attention) classify them (VMEM overflow vs
        kernel bug) and the store must not swallow that signal. Only
        store I/O and (de)serialization failures degrade — loudly — to
        recompilation.

        ``key_by_hlo=True`` appends the lowered program's own hash to the
        filename key — for PROBE sites that compile many sibling
        candidates at identical argument shapes (the candidate geometry
        is baked into the ``pallas_call``), where a shape-stable filename
        would make candidates stale-invalidate each other every sweep.
        """
        t0 = time.perf_counter()
        lowered = jit_fn.lower(*args)
        if not self.enabled:
            compiled = lowered.compile()
            self._note(name, "bypass", None, time.perf_counter() - t0)
            return compiled, "bypass", time.perf_counter() - t0

        try:
            hlo = hashlib.sha256(
                lowered.as_text().encode()
            ).hexdigest()[:16]
        except Exception as e:  # noqa: BLE001 - no text = no safe validity
            logger.warning(
                "AOT: cannot fingerprint lowered program %r (%s: %s); "
                "compiling without the store.", name, type(e).__name__, e,
            )
            compiled = lowered.compile()
            self._note(name, "miss", "unfingerprintable",
                       time.perf_counter() - t0)
            return compiled, "miss", time.perf_counter() - t0

        jax_ver, jaxlib_ver = _jax_versions()
        fingerprint = {
            "code": _code_fingerprint(),
            "jax": jax_ver,
            "jaxlib": jaxlib_ver,
            "hlo": hlo,
        }
        kind = _device_kind()
        if key_by_hlo:
            geometry = f"{geometry}-h{hlo}" if geometry else f"h{hlo}"
        path = self._artifact_path(kind, name, geometry, plan, extra)

        loaded, reason = self._try_load(path, name, fingerprint)
        if loaded is not None:
            seconds = time.perf_counter() - t0
            self._note(name, "hit", None, seconds)
            return loaded, "hit", seconds

        with _genuine_compile():
            compiled = lowered.compile()  # errors propagate to the caller
        self._store(path, compiled, name=name, geometry=geometry,
                    plan=plan, extra=extra, device_kind=kind,
                    fingerprint=fingerprint)
        seconds = time.perf_counter() - t0
        self._note(name, "miss", reason, seconds)
        return compiled, "miss", seconds

    # -- load / store ----------------------------------------------------------

    def _artifact_path(self, kind: str, name: str, geometry: str,
                       plan: str, extra: str) -> Path:
        stem = "--".join(
            _sanitize_part(part) for part in (name, geometry, plan, extra)
        )
        return self.cache_dir / _sanitize_part(kind or "unknown") / f"{stem}.aot"

    def _try_load(self, path: Path, name: str, fingerprint: dict):
        """``(executable, None)`` on a valid load, else ``(None, miss
        reason)``. Stale artifacts are never deserialized; corrupt ones
        are deleted so the recompile's store attempt replaces them."""
        header, blob, problem = _read_artifact(path)
        if problem == "absent":
            return None, "absent"
        if problem is not None:
            logger.warning(
                "AOT: MISS (corrupt) %s — %s; deleting the artifact and "
                "recompiling.", path, problem,
            )
            try:
                path.unlink()
            except OSError:
                pass
            return None, "corrupt"
        stored = header.get("fingerprint") or {}
        changed = [
            c for c in FINGERPRINT_COMPONENTS
            if stored.get(c) != fingerprint.get(c)
        ]
        if changed:
            # the loud stale-invalidation contract: ONE structured line
            # naming each changed component — never deserialize stale
            logger.warning(
                "AOT: MISS (stale) %s — fingerprint changed: %s; "
                "recompiling.", path,
                ", ".join(
                    f"component={c} artifact={stored.get(c)!r} "
                    f"current={fingerprint.get(c)!r}" for c in changed
                ),
            )
            return None, f"stale:{','.join(changed)}"
        try:
            executable = _deserialize(pickle.loads(blob))
        except Exception as e:  # noqa: BLE001 - backend/runtime specific
            logger.warning(
                "AOT: artifact %s exists and is valid but this "
                "backend/runtime cannot deserialize it (%s: %s); falling "
                "back to recompilation.", path, type(e).__name__, e,
            )
            return None, "deserialize"
        return executable, None

    def _store(self, path: Path, compiled, *, name: str, geometry: str,
               plan: str, extra: str, device_kind: str,
               fingerprint: dict) -> None:
        """Serialize + atomically write one artifact (best-effort: a
        store failure costs persistence, never the run)."""
        if self._serialize_unsupported:
            return
        try:
            blob = pickle.dumps(_serialize(compiled))
        except Exception as e:  # noqa: BLE001 - backend specific
            with self._lock:
                first = not self._serialize_unsupported
                self._serialize_unsupported = True
            if first:
                logger.warning(
                    "AOT: this backend cannot serialize compiled programs "
                    "(%s: %s); the store is read-only for this process — "
                    "every program recompiles.", type(e).__name__, e,
                )
            return
        # round-trip validation BEFORE persisting: an executable that XLA's
        # own persistent compile cache deserialized serializes to a payload
        # referencing symbols it does not carry ("Symbols not found" on
        # load) — persisting it would make every warm restart warn-and-
        # recompile. Deserializing here (off the critical path: this is the
        # miss path, the compile already ran) keeps the store hit-or-absent.
        try:
            _deserialize(pickle.loads(blob))
        except Exception as e:  # noqa: BLE001 - backend/runtime specific
            logger.warning(
                "AOT: program %r serialized but its payload does not "
                "deserialize on this backend/runtime (%s: %s); not "
                "persisting it. (A program served from XLA's persistent "
                "compile cache is the known source.)",
                name, type(e).__name__, e,
            )
            return
        header = {
            "store_version": _STORE_VERSION,
            "name": name,
            "geometry": geometry,
            "plan": plan,
            "extra": extra,
            "device_kind": device_kind,
            "fingerprint": dict(fingerprint),
            "blob_bytes": len(blob),
            "blob_sha256": hashlib.sha256(blob).hexdigest(),
            "created": time.time(),
        }
        payload = (
            _MAGIC
            + json.dumps(header, separators=(",", ":")).encode()
            + b"\n"
            + blob
        )
        from ..metrics.artifacts import atomic_write_bytes

        try:
            atomic_write_bytes(path, payload)
        except OSError as e:
            logger.warning(
                "AOT: could not persist artifact %s: %s", path, e,
            )
            return
        with self._lock:
            removed = evict_to_budget(self.cache_dir, self.cache_bytes)
            self.evictions += len(removed)

    # -- accounting ------------------------------------------------------------

    def _note(self, name: str, outcome: str, reason: Optional[str],
              seconds: float) -> None:
        with self._lock:
            if outcome == "hit":
                self.hits += 1
                self.load_times_s.append(seconds)
            elif outcome == "miss":
                self.misses += 1
            else:
                self.bypass += 1
            event: Dict[str, Any] = {
                "name": name, "outcome": outcome,
                "seconds": round(seconds, 6),
            }
            if reason:
                event["reason"] = reason
            self._session.append(event)

    def session_summary(self) -> dict:
        """Provenance for bench.py's JSON line, mirroring the autotuner's:
        overall outcome ('hit' only when every active decision loaded),
        hit/miss/bypass counters and the per-program events."""
        with self._lock:
            if not self.enabled:
                overall = "disabled"
            elif not self._session:
                overall = "unused"
            elif any(e["outcome"] == "miss" for e in self._session):
                overall = "miss"
            else:
                overall = "hit"
            return {
                "cache": overall,
                "hits": self.hits,
                "misses": self.misses,
                "bypass": self.bypass,
                "evictions": self.evictions,
                "load_s_total": round(sum(self.load_times_s), 6),
                "events": [dict(e) for e in self._session],
            }


def probe_compile(name: str, fn, *args, geometry: str = "",
                  extra: str = ""):
    """Route one fused-kernel validation / autotune probe compile through
    the store — the ``jax.jit(fn).lower(*args).compile()`` the kernel
    probes perform, with warm restarts loading the verdict's executable
    instead of re-paying Mosaic. Keyed by the lowered program's own hash
    (``key_by_hlo``), so sibling candidates sharing argument shapes never
    invalidate each other. Compile errors propagate unchanged for the
    caller to classify (VMEM overflow vs kernel bug)."""
    import jax

    return get().load_or_compile(
        name, jax.jit(fn), *args, geometry=geometry, extra=extra,
        key_by_hlo=True,
    )


_instance: Optional[ProgramCache] = None


def get() -> ProgramCache:
    """The process-wide program store (created on first use)."""
    global _instance
    if _instance is None:
        _instance = ProgramCache()
    return _instance


def configure(*, enabled: Optional[bool] = None, cache_dir=None,
              cache_bytes=None) -> ProgramCache:
    """(Re)configure the process-wide store — the CLI/bench wiring for
    ``--aot_cache`` / ``--aot_cache_bytes``."""
    inst = get()
    if enabled is not None:
        inst.enabled = enabled
    if cache_dir is not None:
        inst.set_cache_dir(cache_dir)
    if cache_bytes is not None:
        inst.cache_bytes = parse_bytes(cache_bytes)
    return inst


def reset() -> ProgramCache:
    """Drop the process-wide store and return a fresh one (tests)."""
    global _instance
    _instance = None
    return get()


# -- inspection CLI (stdlib-only: runs on hosts that only ADMINISTER the
# -- store, no jax import on any path here) ------------------------------------

def _fmt_age(seconds: float) -> str:
    if seconds < 120:
        return f"{seconds:.0f}s"
    if seconds < 7200:
        return f"{seconds / 60:.0f}m"
    if seconds < 172800:
        return f"{seconds / 3600:.1f}h"
    return f"{seconds / 86400:.1f}d"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ml_recipe_tpu.ops.aot",
        description="Inspect / verify / evict the AOT compiled-program "
                    "artifact store.",
    )
    parser.add_argument(
        "--cache_dir", default=None,
        help="store root (default: $MLRT_AOT_CACHE or artifacts/aot)")
    parser.add_argument(
        "--list", action="store_true",
        help="enumerate artifacts with key, size, age and fingerprint")
    parser.add_argument(
        "--verify", action="store_true",
        help="check every artifact's header + blob checksum; corrupt or "
             "truncated blobs are reported (exit 1), not deleted")
    parser.add_argument(
        "--evict", action="store_true",
        help="prune oldest artifacts until the store fits "
             "--aot_cache_bytes")
    parser.add_argument(
        "--aot_cache_bytes", default=None,
        help="byte budget for --evict (K/M/G suffixes, e.g. 512M)")
    args = parser.parse_args(argv)

    cache_dir = Path(args.cache_dir) if args.cache_dir else default_cache_dir()
    if not (args.list or args.verify or args.evict):
        args.list = True

    status = 0
    if args.list:
        rows = list(_iter_artifacts(cache_dir))
        if not rows:
            print(f"AOT store {cache_dir}: empty")
        else:
            now = time.time()
            total = 0
            for path, header, problem in rows:
                try:
                    st = path.stat()
                except OSError:
                    continue
                total += st.st_size
                fp = (header or {}).get("fingerprint") or {}
                fp_text = (
                    " ".join(f"{k}={fp.get(k)}"
                             for k in FINGERPRINT_COMPONENTS)
                    if fp else f"<{problem}>"
                )
                print(
                    f"{path.relative_to(cache_dir)}  "
                    f"{st.st_size}B  age={_fmt_age(max(0.0, now - st.st_mtime))}  "
                    f"{fp_text}"
                )
            print(f"total: {len(rows)} artifact(s), {total} bytes")
    if args.verify:
        rows = verify_store(cache_dir)
        bad = [r for r in rows if r["status"] != "ok"]
        for row in rows:
            print(f"{row['status'].upper():<40}  {row['path']}")
        print(
            f"verified {len(rows)} artifact(s): {len(rows) - len(bad)} ok, "
            f"{len(bad)} corrupt"
        )
        if bad:
            status = 1
    if args.evict:
        budget = parse_bytes(args.aot_cache_bytes)
        if budget is None:
            parser.error("--evict requires --aot_cache_bytes (e.g. 512M)")
        removed = evict_to_budget(cache_dir, budget)
        for path in removed:
            print(f"evicted {path}")
        print(f"evicted {len(removed)} artifact(s)")
    return status


if __name__ == "__main__":
    raise SystemExit(main())
