"""Fused attention Pallas TPU kernel.

Replaces the HF/CUDA attention internals of the reference's BertModel trunk
(SURVEY.md §2.2) with a first-party kernel. For BERT-class sequence lengths
(<= 2k) the whole K/V for one (batch, head) fits in VMEM, so the kernel is an
*exact* fused softmax-attention: scores for one query block are computed,
softmaxed and contracted against V entirely on-chip — the [B, H, L, L] score
tensor never exists in HBM (that tensor is the HBM-bandwidth bottleneck of
the naive path).

Layout: q/k/v arrive as [B, L, H, D] (the encoder's natural layout — no
transposes inserted). Grid is (B, H, L/q_blk); each program computes one
query block against the full keys.

Backward: the kernel carries a ``jax.custom_vjp`` whose backward pass
recomputes attention with the XLA einsum path and differentiates that —
forward (the inference/serving hot path and 1/3 of training FLOPs) runs the
kernel, gradients stay exact.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _attention_kernel(mask_ref, q_ref, k_ref, v_ref, o_ref, *, scale: float):
    """One (batch, head, q-block) program: softmax(q k^T) v, fully in VMEM.

    Block shapes (leading singleton dims indexed away by the grid; inputs are
    pre-transposed to [B, H, L, D] so the trailing block dims [q_blk/L, D]
    satisfy the TPU (8, 128)-or-equal tiling rule):
      q_ref: [1, 1, q_blk, D]; k_ref/v_ref: [1, 1, L, D]; mask_ref: [1, 1, L]
      o_ref: [1, 1, q_blk, D]
    """
    q = q_ref[0, 0, :, :]  # [q_blk, D]
    k = k_ref[0, 0, :, :]  # [L, D]
    v = v_ref[0, 0, :, :]  # [L, D]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [q_blk, L] in f32 on the MXU
    s = s * scale

    mask = mask_ref[0, 0, :]  # [L]
    s = jnp.where(mask[None, :] > 0, s, _NEG_INF)

    # numerically-stable softmax in f32 on the VPU
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    p = p / denom

    o = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [q_blk, D]
    o_ref[0, 0, :, :] = o.astype(o_ref.dtype)


def _pick_q_block(L: int) -> Optional[int]:
    for blk in (512, 256, 128):
        if L % blk == 0:
            return blk
    if L <= 512:
        return L  # single block
    return None


def _flash_forward(q, k, v, mask, dtype, interpret: bool = False):
    B, L, H, D = q.shape
    q_blk = _pick_q_block(L)
    assert q_blk is not None, f"unsupported sequence length {L}"

    scale = 1.0 / (D ** 0.5)
    grid = (B, H, L // q_blk)

    kernel = functools.partial(_attention_kernel, scale=scale)

    # [B, L, H, D] -> [B, H, L, D]: trailing block dims become [len, D],
    # satisfying the TPU tile rule; XLA fuses the transposes into the
    # surrounding projection matmuls.
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    mask3 = mask[:, None, :]

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, L), lambda b, h, qi: (b, 0, 0)),          # mask
            pl.BlockSpec((1, 1, q_blk, D), lambda b, h, qi: (b, h, qi, 0)),  # q
            pl.BlockSpec((1, 1, L, D), lambda b, h, qi: (b, h, 0, 0)),       # k
            pl.BlockSpec((1, 1, L, D), lambda b, h, qi: (b, h, 0, 0)),       # v
        ],
        out_specs=pl.BlockSpec((1, 1, q_blk, D), lambda b, h, qi: (b, h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, L, D), dtype),
        interpret=interpret,
    )(mask3, qt, kt, vt)
    return jnp.transpose(out, (0, 2, 1, 3))


def _xla_reference(q, k, v, mask, dtype):
    """Einsum attention used for the backward pass — the dispatcher's XLA
    path itself, so forward-kernel and backward semantics cannot drift."""
    from .attention import _xla_attention

    return _xla_attention(q, k, v, mask, dtype=dtype).astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def flash_attention(q, k, v, mask, dtype=jnp.float32, interpret=False):
    """Fused attention over [B, L, H, D] with a [B, L] key-validity mask."""
    if mask is None:
        mask = jnp.ones(q.shape[:2], dtype=jnp.int32)
    return _flash_forward(q, k, v, mask, dtype, interpret)


def _fwd(q, k, v, mask, dtype, interpret):
    out = flash_attention(q, k, v, mask, dtype, interpret)
    return out, (q, k, v, mask)


def _bwd(dtype, interpret, residuals, g):
    q, k, v, mask = residuals
    _, vjp = jax.vjp(lambda q_, k_, v_: _xla_reference(q_, k_, v_, mask, dtype), q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


flash_attention.defvjp(_fwd, _bwd)
